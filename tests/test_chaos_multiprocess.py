"""Chaos drills across REAL process boundaries.

Each test injects one fault kind from tpu_ddp/resilience/chaos.py into a
2-process localhost cluster (the test_multiprocess.py topology: separate
OS processes, jax.distributed rendezvous, cross-process collectives) and
asserts the matching recovery mechanism engages:

- ``nan-grad`` → the step guard skips the update on BOTH ranks (the
  poisoned gradient crosses the all-reduce), replicas stay bitwise
  identical, and training completes.
- ``stalled-step`` → the launcher's heartbeat watchdog kills the hung
  cluster well before the overall timeout and ``launch_elastic``
  restarts it to completion.
- ``corrupt-ckpt`` + ``hard-exit`` → the restarted run quarantines the
  damaged newest checkpoint and resumes from the previous verified one.
- ``host-loss`` / ``hard-exit`` under ``elastic_reshard`` → the
  SURVIVOR reshards its live TrainState onto the shrunken world (no
  restart, no checkpoint restore), for both the announced and the
  unannounced death.
- ``host-join`` under ``elastic_reshard`` → the departed worker rejoins
  a regrown epoch and restores from the survivors' state beacon.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from tpu_ddp.launch import launch, launch_elastic

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

SMOKE_ENV = {
    "TPU_DDP_SYNTH_SIZE": "64",
    "TPU_DDP_MAX_ITERS": "3",
    "TPU_DDP_GLOBAL_BATCH": "16",
    "CIFAR10_DIR": "/nonexistent-so-synthetic",
}


def _skipped_steps(metrics_path):
    events = [json.loads(l)
              for l in open(metrics_path).read().splitlines()]
    return [e["step"] for e in events if e["event"] == "step_skipped"]


def test_nan_grad_skipped_on_all_ranks(tmp_path):
    """Satellite (d): a NaN gradient injected on ONE rank at step 2 is
    skipped on BOTH (the poison crosses the all-reduce, the guard flag
    is psum-agreed), the per-step replica check stays clean, and the
    epoch completes with identical eval on both ranks."""
    env = dict(SMOKE_ENV)
    env.update({
        "TPU_DDP_CHAOS_FAULTS": "nan-grad@2:rank=1",
        "TPU_DDP_CHAOS_SENTINEL": str(tmp_path / "sentinels"),
        "TPU_DDP_CHECK_REPLICAS_EVERY": "1",  # divergence would raise
        "TPU_DDP_METRICS_FILE": str(tmp_path / "metrics_{rank}.jsonl"),
    })
    res = launch("part3", nproc=2, env=env, echo=False, timeout=600)
    assert res.ok, "\n".join(w.output for w in res.workers)
    # BOTH ranks skipped exactly step 2 — a rank-local skip would have
    # tripped the replica check and failed the run.
    for rank in (0, 1):
        skipped = _skipped_steps(str(tmp_path / f"metrics_{rank}.jsonl"))
        assert skipped == [2], (rank, skipped)
        assert "Test set: average loss" in res.output_of(rank)
    # Synchronized params -> identical eval lines (invariant (ii)).
    line0 = [l for l in res.output_of(0).splitlines() if "Test set" in l]
    line1 = [l for l in res.output_of(1).splitlines() if "Test set" in l]
    assert line0 == line1
    # The injection actually happened where configured.
    assert "injecting nan-grad at step 2" in res.output_of(1)


def test_watchdog_recovers_hung_cluster(tmp_path, capfd):
    """A rank wedged mid-step (stalled-step chaos: one rank sleeps for
    an hour, the other blocks in the next collective) is detected by the
    heartbeat watchdog in ~heartbeat_timeout seconds — NOT the 600 s
    overall timeout — and the elastic restart completes the run from the
    mid-epoch checkpoint."""
    env = dict(SMOKE_ENV)
    env.update({
        "TPU_DDP_CHAOS_FAULTS": "stalled-step@2",
        "TPU_DDP_CHAOS_SENTINEL": str(tmp_path / "sentinels"),
        "TPU_DDP_CKPT_EVERY": "1",
    })
    t0 = time.monotonic()
    res = launch_elastic(
        "part3", nproc=2, max_restarts=1, min_restart_interval=0.0,
        echo=False, timeout=600, heartbeat_timeout=20.0,
        extra_args=["--ckpt-dir", str(tmp_path / "ckpt")], env=env)
    elapsed = time.monotonic() - t0
    assert res.ok, "\n".join(w.output for w in res.workers)
    assert res.restarts == 1
    # Two attempts, each bounded by compile + a few steps + the 20 s
    # stall deadline: far below one attempt's 600 s timeout.
    assert elapsed < 500, elapsed
    out = capfd.readouterr().out
    assert "heartbeat stall" in out
    assert "resumed from" in res.output_of(0)


def test_corrupt_checkpoint_falls_back_on_restart(tmp_path):
    """Combined drill: at step 2 the writer corrupts the newest
    checkpoint, then hard-exits. The restarted run must quarantine the
    corpse and resume from the previous verified checkpoint (step 1),
    not die on the truncated npz."""
    ckpt_dir = tmp_path / "ckpt"
    env = dict(SMOKE_ENV)
    env.update({
        "TPU_DDP_CHAOS_FAULTS": "corrupt-ckpt@2,hard-exit@2",
        "TPU_DDP_CHAOS_SENTINEL": str(tmp_path / "sentinels"),
        "TPU_DDP_CKPT_EVERY": "1",
    })
    res = launch_elastic(
        "part3", nproc=2, max_restarts=1, min_restart_interval=0.0,
        echo=False, timeout=600,
        extra_args=["--ckpt-dir", str(ckpt_dir)], env=env)
    assert res.ok, "\n".join(w.output for w in res.workers)
    assert res.restarts == 1
    out0 = res.output_of(0)
    # Resumed from step 1 — step 2's checkpoint was the corrupt one.
    assert "resumed from" in out0 and "at step 1" in out0, out0
    assert "Test set: average loss" in out0
    # The corpse was quarantined for post-mortem, never deleted.
    quarantined = [d for d in os.listdir(ckpt_dir) if ".corrupt" in d]
    assert any(d.startswith("step_00000002") for d in quarantined), \
        sorted(os.listdir(ckpt_dir))


def test_elastic_reshard_survives_host_loss(tmp_path):
    """The tentpole drill: rank 1 is gracefully preempted at step 2
    under elastic_reshard. The SURVIVOR must pull its live TrainState
    to host, rebuild the one-process world, reshard, and finish the
    run — zero restarts, zero checkpoint restores."""
    env = dict(SMOKE_ENV)
    env.update({
        "TPU_DDP_CHAOS_FAULTS": "host-loss@2:rank=1",
        "TPU_DDP_CHAOS_SENTINEL": str(tmp_path / "sentinels"),
        "TPU_DDP_ELASTIC_RESHARD": "1",
    })
    res = launch("part3", nproc=2, env=env, echo=False, timeout=600,
                 elastic_reshard=True)
    assert res.ok, "\n".join(w.output for w in res.workers)
    assert res.reshards == 1
    # The departed rank's exit was absorbed, not counted as a failure.
    assert [(w.rank, w.absorbed) for w in res.workers
            if w.returncode != 0] == [(1, True)]
    out0 = res.output_of(0)
    assert "resharded in" in out0
    assert "resumed from" not in out0        # live carry, no checkpoint
    assert "Test set: average loss" in out0  # training went on to eval


def test_elastic_reshard_absorbs_unannounced_crash(tmp_path):
    """The UNANNOUNCED death: hard-exit leaves no departure note, so
    the survivor first hits the failed gloo collective, then must wait
    for the launcher to publish the shrunken epoch and convert the
    wreckage into a membership change (engine._raise_membership_change)
    instead of dying on the XlaRuntimeError."""
    env = dict(SMOKE_ENV)
    env.update({
        "TPU_DDP_CHAOS_FAULTS": "hard-exit@2:rank=1",
        "TPU_DDP_CHAOS_SENTINEL": str(tmp_path / "sentinels"),
        "TPU_DDP_ELASTIC_RESHARD": "1",
    })
    res = launch("part3", nproc=2, env=env, echo=False, timeout=600,
                 elastic_reshard=True)
    assert res.ok, "\n".join(w.output for w in res.workers)
    assert res.reshards == 1
    out0 = res.output_of(0)
    assert "resharded in" in out0
    assert "resumed from" not in out0
    assert "Test set: average loss" in out0


def test_elastic_rejoin_restores_from_beacon(tmp_path):
    """host-join: the worker leaves at step 2 and rejoins — a shrink
    epoch then a regrow epoch, with the joiner restoring the LIVE state
    from the survivors' beacon instead of a checkpoint."""
    env = dict(SMOKE_ENV)
    env.update({
        "TPU_DDP_MAX_ITERS": "8",  # survivor must outlive the rejoin
        "TPU_DDP_CHAOS_FAULTS": "host-join@2:rank=1",
        "TPU_DDP_CHAOS_SENTINEL": str(tmp_path / "sentinels"),
        "TPU_DDP_ELASTIC_RESHARD": "1",
    })
    res = launch("part3", nproc=2, env=env, echo=False, timeout=600,
                 elastic_reshard=True)
    assert res.ok, "\n".join(w.output for w in res.workers)
    assert res.reshards == 2
    assert res.output_of(0).count("resharded in") >= 2
    assert any("joined with beaconed state" in w.output
               for w in res.workers)
    assert all("resumed from" not in w.output for w in res.workers)
