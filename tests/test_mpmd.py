"""MPMD pipeline: per-stage compiled programs + explicit edges compute
the dense model's step; compressed cross-slice edges shrink the wire
within the acceptance envelope; guard-skip and scheduler accounting
work on the rung.
"""

import os
import socket
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.optim import SGD
from tpu_ddp.parallel.compress import EdgeCodec
from tpu_ddp.parallel.mpmd import (MPMDPipeline, SliceTopology,
                                   SocketEdge, mega_edge_hlo,
                                   merge_stage_grads,
                                   split_stage_params,
                                   spmd_pipeline_hlo)
from tpu_ddp.parallel.pipeline import stack_block_params
from tpu_ddp.train.pipeline import StageScheduler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny(**kw):
    cfg = dict(max_seq_len=32, compute_dtype=jnp.float32, num_layers=4)
    cfg.update(kw)
    return make_transformer("TransformerLM-tiny", **cfg)


def _batch(b=4, L=32, seed=5):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, 1024, size=(b, L + 1))
    return (toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32))


def _dense_loss_grads(model, params, x, y):
    from tpu_ddp.ops.loss import softmax_cross_entropy
    from tpu_ddp.parallel.pipeline import unstack_block_params

    def loss_fn(p):
        up = unstack_block_params(p, model.num_layers)
        logits = model.apply(up, x)
        nll = softmax_cross_entropy(
            logits.reshape(-1, logits.shape[-1]), y.reshape(-1))
        return jnp.mean(nll)
    return jax.value_and_grad(loss_fn)(params)


def _max_err(a_tree, b_tree):
    return max(float(jnp.max(jnp.abs(a - b)))
               for a, b in zip(jax.tree.leaves(a_tree),
                               jax.tree.leaves(b_tree)))


class TestStageSplit:
    def test_split_merge_roundtrip(self):
        model = _tiny()
        params = stack_block_params(model.init(jax.random.key(0)))
        stages = split_stage_params(params, 2)
        assert "embed" in stages[0] and "embed" not in stages[1]
        assert "head" in stages[1] and "head" not in stages[0]
        back = merge_stage_grads(stages)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_indivisible_layers_raises(self):
        model = _tiny()
        params = stack_block_params(model.init(jax.random.key(0)))
        with pytest.raises(ValueError, match="divisible"):
            split_stage_params(params, 3)


class TestEdgeCodec:
    def test_none_exact(self):
        c = EdgeCodec("none")
        x = jnp.arange(12.0).reshape(3, 4)
        wire, n = c.encode(x)
        assert n == 4 * 12
        np.testing.assert_array_equal(np.asarray(EdgeCodec.decode(wire)),
                                      np.asarray(x))

    def test_bf16_halves_bytes(self):
        c = EdgeCodec("bf16")
        x = jnp.linspace(-3, 3, 1024).reshape(4, 256)
        wire, n = c.encode(x)
        assert n == 2 * 1024
        got = np.asarray(EdgeCodec.decode(wire))
        np.testing.assert_allclose(got, np.asarray(x), rtol=1e-2,
                                   atol=2e-2)
        assert c.ratio == 2.0

    def test_int8_ratio_and_error_feedback(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 256)), jnp.float32)
        c = EdgeCodec("int8")
        wire, n = c.encode(x)
        # 1 byte/elem + 4-byte scale per 256-block
        assert n == 1024 + 4 * 4
        assert c.ratio > 3.5
        got = np.asarray(EdgeCodec.decode(wire))
        assert np.max(np.abs(got - np.asarray(x))) < 0.1
        # error feedback: the residual carries THIS send's error into
        # the next payload, so the running mean of decoded payloads for
        # a CONSTANT input converges on the input (noef drifts).
        acc = np.zeros_like(got)
        for i in range(16):
            w, _ = c.encode(x)
            acc += np.asarray(EdgeCodec.decode(w))
        ef_err = np.max(np.abs(acc / 16 - np.asarray(x)))
        assert ef_err < 1e-2, ef_err
        # ...while the no-error-feedback variant keeps per-send noise
        c2 = EdgeCodec("int8-noef")
        acc2 = np.zeros_like(got)
        for i in range(16):
            w, _ = c2.encode(x)
            acc2 += np.asarray(EdgeCodec.decode(w))
        noef_err = np.max(np.abs(acc2 / 16 - np.asarray(x)))
        assert ef_err < noef_err

    def test_reset_drops_state(self):
        c = EdgeCodec("int8")
        c.encode(jnp.ones((256,)))
        assert c.bytes_sent > 0 and c._residual is not None
        c.reset()
        assert c.bytes_sent == 0 and c._residual is None

    def test_unknown_spec_rejected(self):
        with pytest.raises(ValueError, match="spec"):
            EdgeCodec("fp8")


class TestTopology:
    def test_even_split_and_cross(self):
        t = SliceTopology.even(4, 2)
        assert t.stage_slice == (0, 0, 1, 1)
        assert t.cross_boundaries() == [1]
        assert not t.is_cross(0) and t.is_cross(1) and not t.is_cross(2)

    def test_single_slice_has_no_cross(self):
        assert SliceTopology.single_slice(4).cross_boundaries() == []

    def test_out_of_order_rejected(self):
        with pytest.raises(ValueError, match="order"):
            SliceTopology((0, 1, 0))


class TestMPMDEquivalence:
    def test_fp32_edges_match_dense(self):
        model = _tiny()
        params = stack_block_params(model.init(jax.random.key(0)))
        x, y = _batch()
        dl, dg = _dense_loss_grads(model, params, x, y)
        pipe = MPMDPipeline(model, 2, 32, num_micro=4, compress="none")
        loss, grads = pipe.step_grads(params, x, y)
        assert abs(float(loss) - float(dl)) < 1e-6
        assert _max_err(dg, grads) < 1e-5
        # intra-slice default topology: nothing compressed
        st = pipe.edge_stats()
        assert st["cross_boundaries"] == []
        assert all(e["ratio"] == 1.0 for e in st["down"] + st["up"])

    @pytest.mark.parametrize("spec,min_ratio,tol", [
        ("bf16", 1.99, 5e-4), ("int8", 3.5, 5e-3)])
    def test_compressed_cross_slice_edges(self, spec, min_ratio, tol):
        model = _tiny()
        params = stack_block_params(model.init(jax.random.key(0)))
        x, y = _batch()
        _, dg = _dense_loss_grads(model, params, x, y)
        pipe = MPMDPipeline(model, 2, 32, num_micro=4,
                            topology=SliceTopology.even(2, 2),
                            compress=spec)
        loss, grads = pipe.step_grads(params, x, y)
        assert np.isfinite(float(loss))
        assert _max_err(dg, grads) < tol
        st = pipe.edge_stats()
        assert st["cross_boundaries"] == [0]
        for e in st["down"] + st["up"]:
            assert e["spec"] == spec
            assert e["ratio"] >= min_ratio, e

    def test_guard_skip_is_noop(self):
        model = _tiny()
        params = stack_block_params(model.init(jax.random.key(0)))
        x, y = _batch()
        pipe = MPMDPipeline(model, 2, 32, num_micro=4, compress="none",
                            optimizer=SGD(learning_rate=0.1))
        pipe._chaos_hook = (
            lambda loss, step: float("nan") if step == 1 else loss)
        from tpu_ddp.resilience.guard import StepGuard
        guard = StepGuard(max_bad_steps=3, log=lambda s: None)
        opt = pipe.init_state(params)
        p, o = params, opt
        skipped_flags = []
        for _ in range(3):
            p_new, o_new, loss, skipped = pipe.train_step(p, o, x, y,
                                                          guard=guard)
            skipped_flags.append(skipped)
            if skipped:
                # the no-op contract: params AND opt state untouched
                for a, b in zip(jax.tree.leaves(p),
                                jax.tree.leaves(p_new)):
                    np.testing.assert_array_equal(np.asarray(a),
                                                  np.asarray(b))
                for a, b in zip(jax.tree.leaves(o),
                                jax.tree.leaves(o_new)):
                    np.testing.assert_array_equal(np.asarray(a),
                                                  np.asarray(b))
            p, o = p_new, o_new
        assert skipped_flags == [False, True, False]
        assert pipe.skipped_steps == 1
        assert guard.total_skipped == 1
        assert guard.consecutive == 0  # clean step reset the streak


class TestStageScheduler:
    def test_classify(self):
        c = StageScheduler.classify
        assert c(True, True) == "steady"
        assert c(True, False) == "warmup"
        assert c(False, True) == "cooldown"
        assert c(False, False) == "idle"

    def test_1f1b_tick_accounting(self):
        # pp=2, M=4, T=6: stage 0 sees warmup 2 / steady 2 / cooldown 2
        # / idle 0; the last stage fuses f==b so it is all-steady with
        # the 2(S-1) bubble ticks idle.
        model = _tiny()
        params = stack_block_params(model.init(jax.random.key(0)))
        x, y = _batch()
        sched = StageScheduler(2, depth=2)
        pipe = MPMDPipeline(model, 2, 32, num_micro=4, compress="none",
                            scheduler=sched)
        pipe.step_grads(params, x, y)
        s0, s1 = sched.stats()["stages"]
        assert (s0["warmup"], s0["steady"], s0["cooldown"],
                s0["idle"]) == (2, 2, 2, 0)
        assert (s1["warmup"], s1["steady"], s1["cooldown"],
                s1["idle"]) == (0, 4, 0, 2)
        assert sched.bubble_fraction(1) == pytest.approx(2 / 6)
        assert s1["bubble_fraction"] == pytest.approx(2 / 6, abs=1e-3)

    def test_step_done_drains_and_beats(self):
        beats = []
        sched = StageScheduler(2, depth=2,
                               heartbeat=lambda step: beats.append(step))
        sched.tick(0, fwd=True, bwd=False, handle=jnp.ones(4))
        assert len(sched.windows[0]) <= 2
        sched.step_done(7)
        assert beats == [7]
        assert len(sched.windows[0]) == 0
        assert sched.steps == 1

    def test_bad_depth_rejected(self):
        with pytest.raises(ValueError):
            StageScheduler(0)
        with pytest.raises(ValueError, match="depth"):
            StageScheduler(2, depth=-1)


class TestSocketTransport:
    def test_socketpair_roundtrip_compressed(self):
        a, b = socket.socketpair()
        tx = SocketEdge(a, EdgeCodec("int8"))
        rx = SocketEdge(b)
        x = jnp.asarray(np.random.default_rng(3).normal(size=(2, 256)),
                        jnp.float32)
        tx.send(x)
        tx.send(2 * x)
        got1 = np.asarray(rx.recv())
        got2 = np.asarray(rx.recv())
        assert np.max(np.abs(got1 - np.asarray(x))) < 0.1
        assert np.max(np.abs(got2 - 2 * np.asarray(x))) < 0.2
        assert tx.stats()["ratio"] > 3.5
        a.close(), b.close()


class TestHLOControls:
    """The round-10 acceptance pair: edge collectives on the compiled
    SPMD pipeline step must be overlappable with stage compute; the
    single mega-edge program must NOT be."""

    def test_positive_and_negative_verdicts(self, devices):
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.utils.hlo_comm import assert_overlap
        model = _tiny()
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
        rep = assert_overlap(
            spmd_pipeline_hlo(model, mesh, 4, 32, 4))
        assert rep["overlapped"]
        with pytest.raises(AssertionError):
            assert_overlap(mega_edge_hlo(model, mesh, 4, 32, 4))


@pytest.mark.slow  # two subprocesses, full jit warmup each
class TestTwoProcessDrill:
    def test_drill_int8_edges(self, tmp_path):
        """The end-to-end MPMD drill: two processes, socket edges, int8
        wire — exit 0 + RESULT OK is the whole contract."""
        port = 29873
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TPU_DDP_MPMD_COMPRESS="int8",
                   TPU_DDP_MPMD_STEPS="3")
        env.pop("XLA_FLAGS", None)
        script = os.path.join(REPO, "examples", "mpmd_train.py")
        common = [sys.executable, script, "--num-nodes", "2",
                  "--master-ip", "127.0.0.1", "--master-port", str(port)]
        p1 = subprocess.Popen(common + ["--rank", "1"], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
        p0 = subprocess.Popen(common + ["--rank", "0"], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
        out1, _ = p1.communicate(timeout=300)
        out0, _ = p0.communicate(timeout=300)
        assert p1.returncode == 0, out1
        assert p0.returncode == 0, out0
        assert "RESULT" in out1 and "OK" in out1, out1
