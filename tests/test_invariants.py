"""Replica-consistency invariant checking and elastic failure recovery.

The reference states its correctness invariants but cannot check them,
and a dead rank simply hangs its cluster (SURVEY.md §5). Here: the
divergence detector catches a corrupted replica, and the elastic
launcher survives an injected mid-training crash by respawning the
cluster and resuming from the last mid-epoch checkpoint.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.utils.invariants import (ReplicaDivergenceError,
                                      check_replica_consistency,
                                      replica_divergence)


def _fabricate_diverged(mesh, values_per_device):
    """A 'replicated' array whose per-device copies actually differ —
    the failure mode the detector exists for."""
    sharding = NamedSharding(mesh, P())
    shape = values_per_device[0].shape
    bufs = [jax.device_put(v, d)
            for v, d in zip(values_per_device, mesh.devices.flatten())]
    return jax.make_array_from_single_device_arrays(shape, sharding, bufs)


class TestReplicaConsistency:
    def test_consistent_params_pass(self, devices):
        mesh = make_mesh(devices[:4])
        params = {"w": jax.device_put(jnp.ones((8, 8)),
                                      NamedSharding(mesh, P()))}
        div = check_replica_consistency(params)
        assert div == {"['w']": 0.0}

    def test_diverged_replica_detected(self, devices):
        mesh = make_mesh(devices[:4])
        good = np.ones((8, 8), np.float32)
        bad = good.copy()
        bad[3, 5] += 0.25  # one element drifted on one device
        arr = _fabricate_diverged(mesh, [good, good, bad, good])
        with pytest.raises(ReplicaDivergenceError, match="w"):
            check_replica_consistency({"w": arr})
        div = replica_divergence({"w": arr})
        assert abs(div["['w']"] - 0.25) < 1e-6

    def test_sharded_leaves_skipped(self, devices):
        """dp-sharded leaves hold legitimately different values and must
        not be flagged."""
        mesh = make_mesh(devices[:4])
        arr = jax.device_put(jnp.arange(16.0).reshape(16, 1),
                             NamedSharding(mesh, P("dp")))
        assert replica_divergence({"g": arr}) == {}

    def test_tolerance(self, devices):
        mesh = make_mesh(devices[:4])
        good = np.ones((4, 4), np.float32)
        near = good + 1e-7
        arr = _fabricate_diverged(mesh, [good, near, good, good])
        check_replica_consistency({"w": arr}, atol=1e-6)  # passes
        with pytest.raises(ReplicaDivergenceError):
            check_replica_consistency({"w": arr}, atol=1e-8)


class TestTrainerIntegration:
    def test_resume_skip_accounting(self, devices):
        """start_iter skips batches without counting them as trained:
        stats report only the iterations this run actually performed."""
        from tpu_ddp.models import get_model
        from tpu_ddp.train.engine import Trainer
        from tpu_ddp.utils.config import TrainConfig

        rng = np.random.default_rng(1)
        batch = (rng.normal(size=(4, 32, 32, 3)).astype(np.float32),
                 rng.integers(0, 10, size=4).astype(np.int32))
        cfg = TrainConfig(global_batch_size=4, log_every=2)
        tr = Trainer(get_model("VGG11", compute_dtype=np.float32), cfg,
                     strategy="fused", mesh=make_mesh(devices[:4]))
        state = tr.init_state()
        state, stats = tr.train_epoch(state, [batch] * 3, start_iter=2,
                                      log=lambda *_: None)
        assert stats["iters"] == 1  # 2 of 3 skipped
        assert state.step == 1

    def test_fault_sentinel_suppresses_refire(self, tmp_path, monkeypatch):
        from tpu_ddp.utils.invariants import maybe_inject_failure

        sentinel = tmp_path / "fired"
        sentinel.write_text("fired at step 2\n")
        monkeypatch.setenv("TPU_DDP_FAIL_AT_STEP", "2")
        monkeypatch.setenv("TPU_DDP_FAIL_SENTINEL", str(sentinel))
        maybe_inject_failure(2)  # would os._exit(13) without the sentinel

    def test_engine_check_passes_on_healthy_run(self, devices):
        from tpu_ddp.models import get_model
        from tpu_ddp.train.engine import Trainer
        from tpu_ddp.utils.config import TrainConfig

        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 10, size=8).astype(np.int32)
        cfg = TrainConfig(check_replicas_every=1, max_iters=2,
                          global_batch_size=8)
        tr = Trainer(get_model("VGG11", compute_dtype=np.float32), cfg,
                     strategy="fused", mesh=make_mesh(devices[:4]))
        state = tr.init_state()
        state, stats = tr.train_epoch(state, [(x, y), (x, y)],
                                      log=lambda *_: None)
        assert stats["iters"] == 2  # both checks passed silently
