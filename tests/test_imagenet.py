"""ResNet-50 / ImageNet-1k stretch config (BASELINE.json configs[4];
no reference counterpart — the reference is VGG-11/CIFAR-10 only)."""

import pytest
import jax
import jax.numpy as jnp
import numpy as np

from tpu_ddp.data.imagenet import (IMAGENET_MEAN, IMAGENET_STD,
                                   create_imagenet_loaders, load_imagenet)
from tpu_ddp.models import get_model
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig


class TestImagenetData:
    def test_synthetic_shapes(self):
        x, y, meta = load_imagenet(split="train", synthetic_size=32,
                                   image_size=64, num_classes=100)
        assert meta["synthetic"]
        assert x.shape == (32, 64, 64, 3) and x.dtype == np.uint8
        assert y.shape == (32,) and int(y.max()) < 100

    def test_synthetic_deterministic(self):
        a = load_imagenet(split="train", synthetic_size=16)[0]
        b = load_imagenet(split="train", synthetic_size=16)[0]
        np.testing.assert_array_equal(a, b)

    def test_loaders_normalize_with_imagenet_constants(self):
        tr, te = create_imagenet_loaders(batch_size=8, synthetic_size=16,
                                         image_size=32, num_classes=10)
        xb, yb = next(iter(te))  # test loader: no augmentation
        raw = te.images_u8[:8].astype(np.float32) / 255.0
        want = (raw - IMAGENET_MEAN) / IMAGENET_STD
        np.testing.assert_allclose(xb, want, atol=1e-6)

    def test_loaders_sharded(self):
        tr0, _ = create_imagenet_loaders(rank=0, world_size=2, batch_size=4,
                                         synthetic_size=16, image_size=32)
        tr1, _ = create_imagenet_loaders(rank=1, world_size=2, batch_size=4,
                                         synthetic_size=16, image_size=32)
        n0 = sum(len(l) for _, l in tr0)
        n1 = sum(len(l) for _, l in tr1)
        assert n0 == n1 == 8  # 16 images split evenly


class TestResNet50Config:
    def test_preset(self):
        cfg = TrainConfig.preset("resnet50_imagenet")
        assert cfg.model == "ResNet50"
        assert cfg.num_classes == 1000
        assert cfg.image_size == 224
        assert cfg.dataset == "imagenet"

    def test_full_res_shapes_via_eval_shape(self):
        """224x224x3 -> 1000 logits, checked abstractly (no FLOPs)."""
        model = get_model("ResNet50")
        params = jax.eval_shape(model.init, jax.random.key(0))
        out = jax.eval_shape(model.apply, params,
                             jax.ShapeDtypeStruct((2, 224, 224, 3),
                                                  jnp.float32))
        assert out.shape == (2, 1000)

    @pytest.mark.slow  # full-depth ResNet-50 compile: ~47 s on 1 core
    def test_train_step_on_mesh(self, devices):
        """Full fused-DP train step with ResNet-50 (reduced image size to
        stay CPU-feasible; the architecture is identical)."""
        cfg = TrainConfig.preset("resnet50_imagenet", image_size=32,
                                 global_batch_size=4)
        model = get_model("ResNet50", num_classes=cfg.num_classes,
                          compute_dtype=jnp.float32)
        from tpu_ddp.parallel.mesh import make_mesh
        tr = Trainer(model, cfg, strategy="fused", mesh=make_mesh(devices[:2]))
        state = tr.init_state()
        rng = np.random.default_rng(0)
        x = rng.integers(0, 256, size=(4, 32, 32, 3)).astype(np.uint8)
        y = rng.integers(0, 1000, size=4).astype(np.int32)
        xb, yb, wb = tr.put_batch(x, y)  # uint8 -> on-device normalization
        state, loss = tr.train_step(state, xb, yb, wb)
        assert np.all(np.isfinite(np.asarray(loss)))
