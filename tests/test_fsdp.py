"""FSDP / ZeRO-3 (part5): parameters sharded 1/N at rest, numerically
equivalent to the fused rung, checkpoint round-trips, eval works from
shards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models import get_model
from tpu_ddp.parallel.mesh import DATA_AXIS, make_mesh
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig
from jax.sharding import PartitionSpec as P


def _batch(n=8, seed=0):  # 8 = smallest slot-divisible batch (dp=4); halves 1-core step time
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 32, 32, 3)).astype(np.float32),
            rng.integers(0, 10, size=n).astype(np.int32))


from conftest import cached_vgg_trainer as _trainer  # noqa: E402


class TestFSDPEquivalence:
    @pytest.mark.slow  # two-step momentum sequence; single-step fsdp
    # equivalence stays in the default tier below
    def test_steps_match_fused(self, devices):
        """Two part5 steps (step 2 exercises momentum through the
        flat layout) produce the same model as part3 — verified through
        the materialized (reassembled) parameters."""
        x, y = _batch()
        fused = _trainer(devices, "fused")
        fs = _trainer(devices, "fsdp")
        s_f = fused.init_state()
        s_z = fs.init_state()
        xb, yb, wb = fused.put_batch(x, y)
        xz, yz, wz = fs.put_batch(x, y)
        for _ in range(2):
            s_f, l_f = fused.train_step(s_f, xb, yb, wb)
            s_z, l_z = fs.train_step(s_z, xz, yz, wz)
        np.testing.assert_allclose(np.asarray(l_z), np.asarray(l_f),
                                   rtol=1e-4, atol=1e-5)
        full = jax.device_get(fs._materialize_params(s_z.params))
        want = jax.device_get(s_f.params)
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(full)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-4, atol=1e-5)

    def test_params_actually_sharded(self, devices):
        """At rest every leaf is flat and 1/dp per device — the memory
        property that IS the point of FSDP."""
        tr = _trainer(devices, "fsdp", dp=4)
        state = tr.init_state()
        for leaf in jax.tree.leaves(state.params):
            assert leaf.ndim == 1
            assert leaf.sharding.spec == P(DATA_AXIS)
            assert leaf.addressable_shards[0].data.size == leaf.size // 4
        for leaf in jax.tree.leaves(state.opt_state):
            assert leaf.sharding.spec == P(DATA_AXIS)

    def test_eval_from_shards(self, devices):
        tr = _trainer(devices, "fsdp", dp=4)
        state = tr.init_state()
        x, y = _batch(n=8)
        out = tr.evaluate(state, [(x, y)], log=lambda *_: None)
        assert 0.0 <= out["test_accuracy"] <= 1.0
        assert np.isfinite(out["test_loss"])

    @pytest.mark.slow  # same-layout fsdp roundtrip is pinned fast by
    # TestLMFSDP::test_checkpoint_roundtrip on the identical save path
    def test_checkpoint_roundtrip(self, devices, tmp_path):
        tr = _trainer(devices, "fsdp", dp=4)
        state = tr.init_state()
        x, y = _batch()
        xb, yb, wb = tr.put_batch(x, y)
        state, _ = tr.train_step(state, xb, yb, wb)
        path = tr.save_checkpoint(str(tmp_path), state)
        assert path is not None
        restored = tr.restore_checkpoint(str(tmp_path))
        assert restored.step == state.step
        # Restored shards land back in the dp-sharded flat layout.
        leaf = jax.tree.leaves(restored.params)[0]
        assert leaf.sharding.spec == P(DATA_AXIS)
        s1, l1 = tr.train_step(state, xb, yb, wb)
        s2, l2 = tr.train_step(restored, xb, yb, wb)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-6)
        # Post-step params flow through the restored MOMENTUM — equality
        # here proves optimizer state survived, not just params.
        for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                        jax.tree.leaves(jax.device_get(s2.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-6)

    @pytest.mark.slow  # roundtrip already covered fast; cross-layout
    def test_checkpoint_is_layout_independent(self, devices, tmp_path):
        """FSDP checkpoints hold canonical shapes: they restore at a
        DIFFERENT dp size and into a replicated (fused) trainer with
        BITWISE-identical state. (Loss equality across dp sizes is not
        asserted for the dp=2 target: VGG's per-replica BatchNorm batch
        statistics legitimately change with the shard size — the
        reference's track_running_stats=False semantics.)"""
        x, y = _batch()
        src = _trainer(devices, "fsdp", dp=4)
        state = src.init_state()
        xb, yb, wb = src.put_batch(x, y)
        state, _ = src.train_step(state, xb, yb, wb)
        src.save_checkpoint(str(tmp_path), state)
        src_params = jax.device_get(src._materialize_params(state.params))
        state, l_src = src.train_step(state, xb, yb, wb)

        # Different dp size: state must round-trip bitwise.
        half = _trainer(devices, "fsdp", dp=2)
        rest = half.restore_checkpoint(str(tmp_path))
        rp = jax.device_get(half._materialize_params(rest.params))
        for a, b in zip(jax.tree.leaves(src_params), jax.tree.leaves(rp)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        xr, yr, wr = half.put_batch(x, y)
        _, l_half = half.train_step(rest, xr, yr, wr)
        assert np.isfinite(float(np.mean(np.asarray(l_half))))

        # Same dp, replicated strategy: training continues identically.
        fused = _trainer(devices, "fused", dp=4)
        rest = fused.restore_checkpoint(str(tmp_path))
        _, l_t = fused.train_step(rest, xb, yb, wb)
        np.testing.assert_allclose(float(np.mean(np.asarray(l_t))),
                                   float(np.mean(np.asarray(l_src))),
                                   rtol=1e-5)

    @pytest.mark.slow  # cross-strategy restore; roundtrip covers fast
    def test_zero_checkpoint_restores_into_fused(self, devices, tmp_path):
        """part4's sharded optimizer state is also canonical on disk."""
        x, y = _batch()
        src = _trainer(devices, "zero", dp=4)
        state = src.init_state()
        xb, yb, wb = src.put_batch(x, y)
        state, _ = src.train_step(state, xb, yb, wb)
        src.save_checkpoint(str(tmp_path), state)
        state, l_src = src.train_step(state, xb, yb, wb)

        fused = _trainer(devices, "fused", dp=4)
        rest = fused.restore_checkpoint(str(tmp_path))
        _, l_t = fused.train_step(rest, xb, yb, wb)
        np.testing.assert_allclose(float(np.mean(np.asarray(l_t))),
                                   float(np.mean(np.asarray(l_src))),
                                   rtol=1e-5)

    def test_requires_mesh(self):
        model = get_model("VGG11", compute_dtype=np.float32)
        with pytest.raises(ValueError, match="mesh"):
            Trainer(model, TrainConfig(), strategy="fsdp", mesh=None)


class TestLMFSDP:
    """FSDP for the LM engine: flat dp-sharded transformer params,
    composing with sequence parallelism."""

    def _tokens(self, b=4, L=33, seed=17):
        rng = np.random.default_rng(seed)
        return rng.integers(0, 1024, size=(b, L))

    def _step(self, devices, dp, sp, mode, tokens):
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import SGD
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:dp * sp], dp=dp, sp=sp)
        tr = LMTrainer(model, mesh, param_sharding=mode,
                       optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                     weight_decay=1e-4))
        state = tr.init_state(seed=5)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, loss = tr.train_step(state, x, y)
        return tr, state, float(np.mean(np.asarray(loss)))

    @pytest.mark.parametrize("dp,sp", [(4, 1), (2, 2)])
    def test_step_matches_replicated(self, devices, dp, sp):
        tokens = self._tokens()
        _, s_ref, l_ref = self._step(devices, dp, sp, "replicated", tokens)
        tr, s_fs, l_fs = self._step(devices, dp, sp, "fsdp", tokens)
        assert abs(l_fs - l_ref) < 1e-4, (dp, sp)
        full = jax.device_get(jax.tree.map(
            lambda x, m: np.asarray(x)[:m.size].reshape(m.shape),
            jax.device_get(s_fs.params), tr.zero3.meta))
        want = jax.device_get(s_ref.params)
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(full)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5,
                                       err_msg=f"dp={dp} sp={sp}")

    def test_params_sharded_at_rest(self, devices):
        tr, state, _ = self._step(devices, 4, 1, "fsdp", self._tokens())
        for leaf in jax.tree.leaves(state.params):
            assert leaf.ndim == 1
            assert leaf.addressable_shards[0].data.size == leaf.size // 4

    def test_checkpoint_roundtrip(self, devices, tmp_path):
        from tpu_ddp.train.lm import make_lm_batch
        tokens = self._tokens()
        tr, state, _ = self._step(devices, 4, 1, "fsdp", tokens)
        path = tr.save_checkpoint(str(tmp_path), state)
        assert path is not None
        restored = tr.restore_checkpoint(str(tmp_path))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        s1, l1 = tr.train_step(state, x, y)
        s2, l2 = tr.train_step(restored, x, y)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-6)
        # Post-step params flow through the restored optimizer moments.
        for a, b in zip(jax.tree.leaves(jax.device_get(s1.params)),
                        jax.tree.leaves(jax.device_get(s2.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-6)

    def test_lm_checkpoint_restores_replicated(self, devices, tmp_path):
        """An LM FSDP checkpoint restores into a replicated trainer."""
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch
        tokens = self._tokens()
        tr, state, _ = self._step(devices, 4, 1, "fsdp", tokens)
        tr.save_checkpoint(str(tmp_path), state)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        _, l_src = tr.train_step(state, x, y)

        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import SGD
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        repl_tr = LMTrainer(model, make_mesh(devices[:4], dp=4),
                            optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                          weight_decay=1e-4))
        rest = repl_tr.restore_checkpoint(str(tmp_path))
        xr, yr = repl_tr.put_batch(*make_lm_batch(tokens))
        _, l_t = repl_tr.train_step(rest, xr, yr)
        np.testing.assert_allclose(float(np.mean(np.asarray(l_t))),
                                   float(np.mean(np.asarray(l_src))),
                                   rtol=1e-5)

    def test_rejects_bogus_mode(self, devices):
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.train.lm import LMTrainer

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        with pytest.raises(ValueError, match="param_sharding"):
            LMTrainer(model, make_mesh(devices[:2], dp=2),
                      param_sharding="bogus")


class TestLMFSDPModelParallel:
    """FSDP x tensor/expert parallelism (round-3 verdict item 3): each
    mp/ep-sharded leaf's flat parameter layout is per model-parallel
    cell, dp-sharded within it (P((mp..., dp)))."""

    def _step(self, devices, mode, tokens, dp=2, sp=1, mp=1, ep=1,
              model_name="TransformerLM-tiny", steps=2):
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import SGD
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer(model_name, max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:dp * sp * mp * ep], dp=dp, sp=sp,
                         mp=mp, ep=ep)
        tr = LMTrainer(model, mesh, param_sharding=mode,
                       optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                     weight_decay=1e-4))
        state = tr.init_state(seed=5)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(steps):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        return tr, state, losses

    def _tokens(self, b=4, L=33, seed=19):
        return np.random.default_rng(seed).integers(0, 1024, size=(b, L))

    @pytest.mark.parametrize("dp,sp,mp", [
        (2, 1, 2),
        # the 3-axis mesh adds one more layout compile over (2,1,2)
        pytest.param(2, 2, 2, marks=pytest.mark.slow)])
    def test_fsdp_tp_matches_replicated(self, devices, dp, sp, mp):
        """Two fsdp steps on a dp x (sp x) tp mesh == the replicated
        dp x tp step (step 2 exercises momentum through the
        partition-aware flat layout)."""
        tokens = self._tokens()
        _, s_ref, l_ref = self._step(devices, "replicated", tokens,
                                     dp=dp, sp=sp, mp=mp)
        tr, s_fs, l_fs = self._step(devices, "fsdp", tokens,
                                    dp=dp, sp=sp, mp=mp)
        np.testing.assert_allclose(l_fs, l_ref, rtol=1e-4)
        full = tr.zero3.unshard_host(jax.device_get(s_fs.params))
        want = jax.device_get(s_ref.params)
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(full)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5,
                                       err_msg=f"dp={dp} sp={sp} mp={mp}")

    def test_fsdp_tp_sharded_at_rest(self, devices):
        """tp-sharded leaves lay out P((mp, dp)) — 1/(mp*dp) per device;
        replicated leaves P(dp)."""
        from tpu_ddp.parallel.mesh import MODEL_AXIS
        tr, state, _ = self._step(devices, "fsdp", self._tokens(),
                                  dp=2, mp=2, steps=1)
        wo = state.params["blocks"][0]["wo"]
        assert wo.ndim == 1
        assert wo.sharding.spec == P((MODEL_AXIS, DATA_AXIS))
        assert wo.addressable_shards[0].data.size == wo.size // 4
        emb = state.params["embed"]
        assert emb.sharding.spec == P(DATA_AXIS)
        assert emb.addressable_shards[0].data.size == emb.size // 2

    def test_fsdp_ep_moe_matches_replicated(self, devices):
        """FSDP composes with expert parallelism: dp2 x ep2 MoE fsdp ==
        the replicated run on the same mesh."""
        tokens = self._tokens(b=8)
        _, s_ref, l_ref = self._step(devices, "replicated", tokens,
                                     dp=2, ep=2,
                                     model_name="TransformerLM-moe-tiny")
        tr, s_fs, l_fs = self._step(devices, "fsdp", tokens, dp=2, ep=2,
                                    model_name="TransformerLM-moe-tiny")
        np.testing.assert_allclose(l_fs, l_ref, rtol=1e-4)
        full = tr.zero3.unshard_host(jax.device_get(s_fs.params))
        want = jax.device_get(s_ref.params)
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(full)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)

    def test_fsdp_tp_checkpoint_into_replicated(self, devices, tmp_path):
        """fsdp x tp checkpoints hold canonical shapes: a replicated
        dp x tp trainer restores and continues identically."""
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import SGD
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        tokens = self._tokens()
        tr, state, _ = self._step(devices, "fsdp", tokens, dp=2, mp=2,
                                  steps=1)
        tr.save_checkpoint(str(tmp_path), state)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        _, l_src = tr.train_step(state, x, y)

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        repl = LMTrainer(model, make_mesh(devices[:4], dp=2, mp=2),
                         optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                       weight_decay=1e-4))
        rest = repl.restore_checkpoint(str(tmp_path))
        xr, yr = repl.put_batch(*make_lm_batch(tokens))
        _, l_t = repl.train_step(rest, xr, yr)
        np.testing.assert_allclose(float(np.mean(np.asarray(l_t))),
                                   float(np.mean(np.asarray(l_src))),
                                   rtol=1e-5)


class TestPipelineFSDP:
    """FSDP within each pipeline stage (round-5, the last structural
    gap of the composition matrix): the stacked block leaves' flat
    layout is partition-aware over pp (P((pp[, mp], dp))), so
    gather_params hands each stage exactly its stacked slice. GPipe
    differentiates through the gather (AD-transpose reduce-scatter);
    1F1B gathers at step start and scatters the full stage-local
    gradients at the end."""

    def _tokens(self, b=8, L=33, seed=5):
        rng = np.random.default_rng(seed)
        return rng.integers(0, 1024, size=(b, L))

    def _run(self, devices, schedule, param_sharding, mp=1, clip=None,
             steps=2, tokens=None):
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import SGD
        from tpu_ddp.train.lm import PipelineLMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4 * mp], dp=2, pp=2, mp=mp)
        tr = PipelineLMTrainer(
            model, mesh, num_micro=2, schedule=schedule,
            param_sharding=param_sharding, clip_grad_norm=clip,
            optimizer=SGD(learning_rate=0.1, momentum=0.9,
                          weight_decay=1e-4))
        state = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(
            tokens if tokens is not None else self._tokens()))
        losses = []
        for _ in range(steps):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        return tr, state, losses

    @pytest.mark.parametrize("schedule", [
        # one fast fsdp-pp cell; gpipe differs only in bubble order
        pytest.param("gpipe", marks=pytest.mark.slow), "1f1b"])
    def test_matches_replicated(self, devices, schedule):
        """Two SGD steps (momentum through the flat layout): fsdp-pp ==
        the replicated pipeline, params compared in canonical shapes."""
        _, s_ref, l_ref = self._run(devices, schedule, "replicated")
        tr, s_f, l_f = self._run(devices, schedule, "fsdp")
        np.testing.assert_allclose(l_f, l_ref, rtol=1e-5)
        p_f = tr.zero3.unshard_host(jax.device_get(s_f.params))
        for a, b in zip(jax.tree.leaves(jax.device_get(s_ref.params)),
                        jax.tree.leaves(p_f)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-5, atol=1e-6,
                                       err_msg=schedule)

    @pytest.mark.slow  # four 8-device compiles; the bare fsdp-pp
    # exactness runs fast above, this pins the x tp x clip frontier
    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_matches_replicated_with_tp_and_clip(self, devices,
                                                 schedule):
        """dp2 x pp2 x tp2 + global-norm clip, BOTH schedules: the flat
        specs carry the (pp, mp, dp) axes and the cross-layout norm
        stays exact (1F1B's clip runs on the post-scatter shards)."""
        _, s_ref, l_ref = self._run(devices, schedule, "replicated",
                                    mp=2, clip=0.5)
        tr, s_f, l_f = self._run(devices, schedule, "fsdp", mp=2,
                                 clip=0.5)
        np.testing.assert_allclose(l_f, l_ref, rtol=1e-5)
        p_f = tr.zero3.unshard_host(jax.device_get(s_f.params))
        for a, b in zip(jax.tree.leaves(jax.device_get(s_ref.params)),
                        jax.tree.leaves(p_f)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-5, atol=1e-6)

    def test_params_sharded_at_rest(self, devices):
        """The memory claim: stacked block leaves live as P((pp, dp))
        flat shards — 1/(pp*dp) of the leaf per device."""
        from jax.sharding import PartitionSpec as P

        from tpu_ddp.parallel.mesh import DATA_AXIS, PIPE_AXIS

        tr, state, _ = self._run(devices, "gpipe", "fsdp", steps=1)
        blk = state.params["blocks"]["wqkv"]
        assert blk.ndim == 1  # flat layout
        assert blk.sharding.spec == P((PIPE_AXIS, DATA_AXIS))
        assert blk.addressable_shards[0].data.size == blk.size // 4
        emb = state.params["embed"]
        assert emb.sharding.spec == P(DATA_AXIS)

    @pytest.mark.slow  # cross-layout restore on top of the fsdp-pp
    # step + layout pins kept fast above; the canonical-checkpoint
    # doctrine itself is pinned fast by the zero/fsdp roundtrips.
    def test_checkpoint_restores_into_replicated(self, devices,
                                                 tmp_path):
        """fsdp-pp checkpoints hold canonical STACKED shapes: the
        replicated pipeline trainer restores and continues
        identically."""
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import SGD
        from tpu_ddp.train.lm import PipelineLMTrainer, make_lm_batch

        tokens = self._tokens()
        tr, state, _ = self._run(devices, "gpipe", "fsdp", steps=1,
                                 tokens=tokens)
        tr.save_checkpoint(str(tmp_path), state)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        cont, _ = tr.train_step(state, x, y)

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        repl = PipelineLMTrainer(
            model, make_mesh(devices[:4], dp=2, pp=2), num_micro=2,
            optimizer=SGD(learning_rate=0.1, momentum=0.9,
                          weight_decay=1e-4))
        resumed = repl.restore_checkpoint(str(tmp_path))
        xr, yr = repl.put_batch(*make_lm_batch(tokens))
        resumed, _ = repl.train_step(resumed, xr, yr)
        cont_p = tr.zero3.unshard_host(jax.device_get(cont.params))
        for a, b in zip(jax.tree.leaves(cont_p),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)

    def test_redundant_opt_sharding_rejected(self, devices):
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.train.lm import PipelineLMTrainer

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=2, pp=2)
        with pytest.raises(ValueError, match="redundant"):
            PipelineLMTrainer(model, mesh, num_micro=2,
                              param_sharding="fsdp",
                              opt_sharding="zero1")

    def test_adafactor_rejected(self, devices):
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import Adafactor
        from tpu_ddp.train.lm import PipelineLMTrainer

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=2, pp=2)
        with pytest.raises(ValueError, match="factored"):
            PipelineLMTrainer(model, mesh, num_micro=2,
                              param_sharding="fsdp",
                              optimizer=Adafactor(
                                  min_dim_size_to_factor=8))
