"""FSDP / ZeRO-3 (part5): parameters sharded 1/N at rest, numerically
equivalent to the fused rung, checkpoint round-trips, eval works from
shards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models import get_model
from tpu_ddp.parallel.mesh import DATA_AXIS, make_mesh
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig
from jax.sharding import PartitionSpec as P


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 32, 32, 3)).astype(np.float32),
            rng.integers(0, 10, size=n).astype(np.int32))


def _trainer(devices, strategy, dp=4):
    mesh = make_mesh(devices[:dp])
    model = get_model("VGG11", compute_dtype=np.float32)
    return Trainer(model, TrainConfig(), strategy=strategy, mesh=mesh)


class TestFSDPEquivalence:
    def test_steps_match_fused(self, devices):
        """Three part5 steps produce the same model as part3 — verified
        through the materialized (reassembled) parameters."""
        x, y = _batch()
        fused = _trainer(devices, "fused")
        fs = _trainer(devices, "fsdp")
        s_f = fused.init_state()
        s_z = fs.init_state()
        xb, yb, wb = fused.put_batch(x, y)
        xz, yz, wz = fs.put_batch(x, y)
        for _ in range(3):
            s_f, l_f = fused.train_step(s_f, xb, yb, wb)
            s_z, l_z = fs.train_step(s_z, xz, yz, wz)
        np.testing.assert_allclose(np.asarray(l_z), np.asarray(l_f),
                                   rtol=1e-4, atol=1e-5)
        full = jax.device_get(fs._materialize_params(s_z.params))
        want = jax.device_get(s_f.params)
        for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(full)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-4, atol=1e-5)

    def test_params_actually_sharded(self, devices):
        """At rest every leaf is flat and 1/dp per device — the memory
        property that IS the point of FSDP."""
        tr = _trainer(devices, "fsdp", dp=4)
        state = tr.init_state()
        for leaf in jax.tree.leaves(state.params):
            assert leaf.ndim == 1
            assert leaf.sharding.spec == P(DATA_AXIS)
            assert leaf.addressable_shards[0].data.size == leaf.size // 4
        for leaf in jax.tree.leaves(state.opt_state):
            assert leaf.sharding.spec == P(DATA_AXIS)

    def test_eval_from_shards(self, devices):
        tr = _trainer(devices, "fsdp", dp=4)
        state = tr.init_state()
        x, y = _batch(n=8)
        out = tr.evaluate(state, [(x, y)], log=lambda *_: None)
        assert 0.0 <= out["test_accuracy"] <= 1.0
        assert np.isfinite(out["test_loss"])

    def test_checkpoint_roundtrip(self, devices, tmp_path):
        tr = _trainer(devices, "fsdp", dp=4)
        state = tr.init_state()
        x, y = _batch()
        xb, yb, wb = tr.put_batch(x, y)
        state, _ = tr.train_step(state, xb, yb, wb)
        path = tr.save_checkpoint(str(tmp_path), state)
        assert path is not None
        restored = tr.restore_checkpoint(str(tmp_path))
        assert restored.step == state.step
        # Restored shards land back in the dp-sharded flat layout.
        leaf = jax.tree.leaves(restored.params)[0]
        assert leaf.sharding.spec == P(DATA_AXIS)
        s1, l1 = tr.train_step(state, xb, yb, wb)
        s2, l2 = tr.train_step(restored, xb, yb, wb)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-6)

    def test_requires_mesh(self):
        model = get_model("VGG11", compute_dtype=np.float32)
        with pytest.raises(ValueError, match="mesh"):
            Trainer(model, TrainConfig(), strategy="fsdp", mesh=None)
