"""ZeRO-1 sharded optimizer (part4): numerically equivalent to the fused
rung (part3), with optimizer state actually sharded 1/N per dp worker.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_ddp.models import get_model
from tpu_ddp.ops.optim import SGD, AdamW
from tpu_ddp.parallel.mesh import DATA_AXIS, make_mesh
from tpu_ddp.parallel.zero import ZeRO1
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig


def _batch(n=8, seed=0):  # 8 = smallest slot-divisible batch (dp=4); halves 1-core step time
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


from conftest import cached_vgg_trainer as _trainer  # noqa: E402


class TestZeROEquivalence:
    @pytest.mark.slow  # two-step momentum sequence; single-step zero1
    # equivalence and the checkpoint roundtrip stay in the default tier
    def test_steps_match_fused(self, devices):
        """Two part4 steps produce the same parameters as part3 (two,
        not one: step 2 exercises momentum carried in the flat layout)."""
        x, y = _batch()
        results = {}
        for strategy in ("fused", "zero"):
            tr = _trainer(devices, strategy)
            state = tr.init_state()
            xb, yb, wb = tr.put_batch(x, y)
            for _ in range(2):
                state, loss = tr.train_step(state, xb, yb, wb)
            results[strategy] = (jax.device_get(state.params),
                                 float(np.mean(np.asarray(loss))))
        p_fused, l_fused = results["fused"]
        p_zero, l_zero = results["zero"]
        assert abs(l_fused - l_zero) < 1e-4
        for a, b in zip(jax.tree.leaves(p_fused), jax.tree.leaves(p_zero)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-4, atol=1e-5)

    def test_opt_state_is_sharded(self, devices):
        """Momentum leaves live 1/dp per device (flat, dp-sharded), unlike
        the replicated fused strategy."""
        tr = _trainer(devices, "zero", dp=4)
        state = tr.init_state()
        leaves = jax.tree.leaves(state.opt_state)
        for leaf in leaves:
            assert leaf.ndim == 1  # flattened
            assert leaf.size % 4 == 0  # padded to dp divisibility
            shard = leaf.addressable_shards[0]
            assert shard.data.size == leaf.size // 4  # 1/dp per device
            assert leaf.sharding.spec == P(DATA_AXIS)

    def test_params_stay_replicated_and_identical(self, devices):
        tr = _trainer(devices, "zero", dp=4)
        state = tr.init_state()
        x, y = _batch()
        xb, yb, wb = tr.put_batch(x, y)
        state, _ = tr.train_step(state, xb, yb, wb)
        leaf = jax.tree.leaves(state.params)[0]
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)

    def test_checkpoint_roundtrip(self, devices, tmp_path):
        tr = _trainer(devices, "zero", dp=4)
        state = tr.init_state()
        x, y = _batch()
        xb, yb, wb = tr.put_batch(x, y)
        state, _ = tr.train_step(state, xb, yb, wb)
        path = tr.save_checkpoint(str(tmp_path), state)
        assert path is not None
        restored = tr.restore_checkpoint(str(tmp_path))
        assert restored.step == state.step
        for a, b in zip(jax.tree.leaves(jax.device_get(state.params)),
                        jax.tree.leaves(jax.device_get(restored.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # Training continues identically from the restored state.
        s1, l1 = tr.train_step(state, xb, yb, wb)
        s2, l2 = tr.train_step(restored, xb, yb, wb)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-6)


class TestZeROWrapper:
    def test_adamw_decay_mask_preserved(self, devices):
        """Flattening must not change which leaves get weight decay: a
        ZeRO-AdamW step on a {matrix, bias} tree equals dense AdamW."""
        mesh = make_mesh(devices[:4])
        params = {"w": jnp.asarray(np.random.default_rng(0).normal(
            size=(8, 6)).astype(np.float32)),
            "b": jnp.ones((6,), jnp.float32)}
        grads = jax.tree.map(jnp.ones_like, params)

        dense = AdamW(weight_decay=0.5)
        d_state = dense.init(params)
        d_new, _ = dense.apply(params, grads, d_state)

        zero = ZeRO1(AdamW(weight_decay=0.5), DATA_AXIS, 4)
        z_state = zero.init(params)
        z_state = jax.device_put(
            z_state, jax.tree.map(
                lambda s: jax.sharding.NamedSharding(mesh, s),
                zero.state_specs(),
                is_leaf=lambda x: isinstance(x, P)))

        def step(p, g, s):
            new_p, new_s = zero.apply(p, g, s)
            return new_p, new_s

        opt_spec = zero.state_specs()
        stepped = jax.jit(jax.shard_map(
            step, mesh=mesh,
            in_specs=(P(), P(), opt_spec),
            out_specs=(P(), opt_spec), check_vma=False))
        z_new, _ = stepped(params, grads, z_state)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(z_new[k]),
                                       np.asarray(d_new[k]),
                                       rtol=1e-6, atol=1e-7,
                                       err_msg=k)

    def test_requires_axis_size(self):
        with pytest.raises(ValueError, match="axis size"):
            ZeRO1(SGD(), DATA_AXIS, None)

    def test_padding_tail_stays_zero(self, devices):
        """A leaf whose size is not divisible by dp pads with zeros; the
        pad region must never contaminate the reassembled params."""
        mesh = make_mesh(devices[:4])
        params = {"v": jnp.arange(10, dtype=jnp.float32)}  # 10 % 4 != 0
        grads = {"v": jnp.ones((10,), jnp.float32)}
        zero = ZeRO1(SGD(learning_rate=0.1, momentum=0.0,
                         weight_decay=0.0), DATA_AXIS, 4)
        z_state = zero.init(params)
        opt_spec = zero.state_specs()
        z_state = jax.device_put(
            z_state, jax.tree.map(
                lambda s: jax.sharding.NamedSharding(mesh, s), opt_spec,
                is_leaf=lambda x: isinstance(x, P)))
        stepped = jax.jit(jax.shard_map(
            lambda p, g, s: zero.apply(p, g, s), mesh=mesh,
            in_specs=(P(), P(), opt_spec), out_specs=(P(), opt_spec),
            check_vma=False))
        new_p, _ = stepped(params, grads, z_state)
        want = np.arange(10, dtype=np.float32) - 0.1
        np.testing.assert_allclose(np.asarray(new_p["v"]), want, rtol=1e-6)


class TestZeRO1ModelParallel:
    """ZeRO-1 composed with tensor/expert parallelism (round-3 verdict
    item 6): each mp/ep-sharded leaf's optimizer state is laid out per
    model-parallel cell and dp-sharded within it (P((mp, dp)))."""

    def _lm(self, devices, sharding, mp=1, ep=1, model_name
            ="TransformerLM-tiny", seed=7):
        import jax.numpy as jnp
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.train.lm import LMTrainer

        model = make_transformer(model_name, max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=4 // (mp * ep), mp=mp, ep=ep)
        return LMTrainer(model, mesh, optimizer=AdamW(),
                         opt_sharding=sharding)

    def _run(self, tr, tokens, steps=3):
        from tpu_ddp.train.lm import make_lm_batch
        state = tr.init_state(seed=0)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(steps):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        return state, losses

    def test_dp_tp_zero1_matches_replicated_opt(self, devices):
        """dp2 x tp2 with zero1 == dp2 x tp2 with replicated optimizer:
        same losses AND same final params, leaf for leaf — plus the
        state-layout claims (one trainer run serves both, 1-core CI)."""
        from tpu_ddp.parallel.mesh import MODEL_AXIS
        tokens = np.random.default_rng(11).integers(0, 1024, size=(4, 33))
        s_z, l_z = self._run(self._lm(devices, "zero1", mp=2), tokens,
                             steps=2)
        s_r, l_r = self._run(self._lm(devices, "replicated", mp=2),
                             tokens, steps=2)
        np.testing.assert_allclose(l_z, l_r, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(jax.device_get(s_r.params)),
                        jax.tree.leaves(jax.device_get(s_z.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)
        # Layout (on the stepped state): tp-sharded leaves' moments
        # shard P((mp, dp)), replicated leaves' P(dp); each device owns
        # 1/(mp*dp).
        mu = s_z.opt_state["mu"]
        leaf = mu["blocks"][0]["wqkv"]  # (dm, 3, heads, hd), heads/mp
        assert leaf.sharding.spec == P((MODEL_AXIS, DATA_AXIS))
        assert mu["embed"].sharding.spec == P(DATA_AXIS)
        assert leaf.addressable_shards[0].data.size == leaf.size // 4

    def test_dp_tp_zero1_checkpoint_into_replicated(self, devices,
                                                    tmp_path):
        """A dp x tp zero1 checkpoint holds canonical shapes: a plain
        dp-only replicated trainer restores and continues identically."""
        import jax.numpy as jnp
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        tokens = np.random.default_rng(12).integers(0, 1024, size=(4, 33))
        tr = self._lm(devices, "zero1", mp=2)
        state = tr.init_state(seed=0)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        repl = LMTrainer(model, make_mesh(devices[:4]), optimizer=AdamW())
        resumed = repl.restore_checkpoint(str(tmp_path))
        xr, yr = repl.put_batch(*make_lm_batch(tokens))
        resumed, _ = repl.train_step(resumed, xr, yr)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)

    @pytest.mark.slow  # ep sharding is orthogonal to the zero1 flat
    # partition; dp x tp stays fast and moe/adafactor pin ep itself
    def test_dp_ep_zero1_matches_replicated_opt(self, devices):
        """dp2 x ep2 MoE with zero1 == same mesh with replicated
        optimizer (expert leaves' ep-sum/dp-mean algebra preserved)."""
        tokens = np.random.default_rng(13).integers(0, 1024, size=(8, 33))
        runs = {s: self._run(self._lm(devices, s, ep=2,
                                      model_name="TransformerLM-moe-tiny"),
                             tokens, steps=2)
                for s in ("replicated", "zero1")}
        np.testing.assert_allclose(runs["zero1"][1], runs["replicated"][1],
                                   rtol=1e-5)
        for a, b in zip(
                jax.tree.leaves(jax.device_get(runs["replicated"][0].params)),
                jax.tree.leaves(jax.device_get(runs["zero1"][0].params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)

    def test_adafactor_tp_composes_per_cell(self, devices):
        """Round-5: the old tp refusal is gone — zero1 Adafactor under
        tp goes through the partition-aware FactoredZeRO1 (per-cell
        factoring; exactness pinned in tests/test_adafactor.py) and
        takes a finite first step."""
        import jax.numpy as jnp
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import Adafactor
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=2, mp=2)
        tr = LMTrainer(model, mesh,
                       optimizer=Adafactor(min_dim_size_to_factor=8),
                       opt_sharding="zero1")
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(2).integers(0, 1024, size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, loss = tr.train_step(state, x, y)
        assert np.isfinite(float(np.mean(np.asarray(loss))))


class TestZeRO1Pipeline:
    """ZeRO-1 under pipeline parallelism (round-3 verdict item 9):
    stacked block leaves' optimizer state shards P((pp, dp))."""

    def _run(self, devices, sharding, schedule="gpipe", steps=2, mp=1,
             sp=1):
        import jax.numpy as jnp
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.train.lm import PipelineLMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4 * mp * sp], dp=2, pp=2, mp=mp, sp=sp)
        tr = PipelineLMTrainer(model, mesh, num_micro=2,
                               optimizer=AdamW(), schedule=schedule,
                               opt_sharding=sharding)
        tokens = np.random.default_rng(21).integers(0, 1024, size=(4, 17))
        state = tr.init_state(seed=0)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(steps):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        return tr, state, losses

    def test_pp_zero1_matches_replicated_opt(self, devices):
        """One pair of gpipe runs serves three claims (1-core CI):
        zero1 == replicated-opt losses AND params; the P((pp, dp))
        state layout; and the decay policy on stacked (L, dm) LN
        scales (rank+1 would otherwise flip it — their exact agreement
        with the replicated run is the proof)."""
        from tpu_ddp.parallel.mesh import PIPE_AXIS
        _, s_repl, l_repl = self._run(devices, "replicated")
        _, s_zero, l_zero = self._run(devices, "zero1")
        np.testing.assert_allclose(l_zero, l_repl, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(jax.device_get(s_repl.params)),
                        jax.tree.leaves(jax.device_get(s_zero.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)
        mu = s_zero.opt_state["mu"]
        blk_leaf = jax.tree.leaves(mu["blocks"])[0]
        assert blk_leaf.sharding.spec == P((PIPE_AXIS, DATA_AXIS))
        assert mu["embed"].sharding.spec == P(DATA_AXIS)
        # One (pp, dp) cell owns 1/4 of a stacked leaf's state.
        assert (blk_leaf.addressable_shards[0].data.size
                == blk_leaf.size // 4)

    # The gpipe-schedule equivalence above pins pp x zero1; 1f1b only
    # reorders the already-tested microbatch schedule on top.
    @pytest.mark.slow
    def test_pp_zero1_1f1b(self, devices):
        """The hand-scheduled 1F1B backward feeds the same ZeRO update."""
        _, s_repl, l_repl = self._run(devices, "replicated",
                                      schedule="1f1b")
        _, s_zero, l_zero = self._run(devices, "zero1", schedule="1f1b")
        np.testing.assert_allclose(l_zero, l_repl, rtol=1e-5)

    @pytest.mark.slow  # axis-orthogonal to the default-tier pp-zero1
    # and pp-sp cells; the composition itself is what this pins
    def test_pp_zero1_sp(self, devices):
        """ZeRO-1 under pp x sp (round 4): the dp-scattered state rides
        the sequence-parallel pipeline — same losses and params as the
        replicated-optimizer run on the identical mesh."""
        _, s_repl, l_repl = self._run(devices, "replicated", sp=2)
        _, s_zero, l_zero = self._run(devices, "zero1", sp=2)
        np.testing.assert_allclose(l_zero, l_repl, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(jax.device_get(s_repl.params)),
                        jax.tree.leaves(jax.device_get(s_zero.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)

    @pytest.mark.slow  # cross-layout zero1 restore is pinned fast by
    # test_dp_tp_zero1_checkpoint_into_replicated; this adds the pp axis
    def test_pp_zero1_checkpoint_into_replicated(self, devices,
                                                 tmp_path):
        import jax.numpy as jnp
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.train.lm import PipelineLMTrainer, make_lm_batch

        tr, state, _ = self._run(devices, "zero1", steps=1)
        tokens = np.random.default_rng(22).integers(0, 1024, size=(4, 17))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)

        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        repl = PipelineLMTrainer(model,
                                 make_mesh(jax.devices()[:4], dp=2, pp=2),
                                 num_micro=2, optimizer=AdamW())
        resumed = repl.restore_checkpoint(str(tmp_path))
        resumed, _ = repl.train_step(resumed, x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)

    @pytest.mark.slow  # triple compose; pp x zero1 and dp x tp x zero1
    # each stay fast, the tp leg adds no new partition logic
    def test_pp_zero1_tp_matches_replicated_opt(self, devices):
        """dp2 x pp2 x tp2 (round-4: the multi-axis partition): stacked
        tp leaves' optimizer state lays out P((pp, mp, dp)) — 1/8 per
        device — and the update exactly matches the replicated-optimizer
        run on the same mesh."""
        from tpu_ddp.parallel.mesh import MODEL_AXIS, PIPE_AXIS
        _, s_repl, l_repl = self._run(devices, "replicated", mp=2)
        _, s_zero, l_zero = self._run(devices, "zero1", mp=2)
        np.testing.assert_allclose(l_zero, l_repl, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(jax.device_get(s_repl.params)),
                        jax.tree.leaves(jax.device_get(s_zero.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)
        mu = s_zero.opt_state["mu"]
        wo = mu["blocks"]["wo"]  # stacked (L, h, hd, dm), pp x mp sharded
        assert wo.sharding.spec == P((PIPE_AXIS, MODEL_AXIS, DATA_AXIS))
        assert wo.addressable_shards[0].data.size == wo.size // 8
        ln = mu["blocks"]["ln1"]["scale"]  # stacked (L, dm), pp only
        assert ln.sharding.spec == P((PIPE_AXIS, DATA_AXIS))

    @pytest.mark.slow  # canonicalization is covered fast by the dp-tp
    # checkpoint test; this pins the three-axis composition only
    def test_pp_zero1_tp_checkpoint_into_replicated(self, devices,
                                                    tmp_path):
        """The P((pp, mp, dp)) state canonicalizes: a plain replicated
        pp x tp trainer restores the checkpoint and continues
        identically."""
        import jax.numpy as jnp
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.train.lm import PipelineLMTrainer, make_lm_batch

        tr, state, _ = self._run(devices, "zero1", steps=1, mp=2)
        tokens = np.random.default_rng(23).integers(0, 1024, size=(4, 17))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)

        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        repl = PipelineLMTrainer(
            model, make_mesh(jax.devices()[:8], dp=2, pp=2, mp=2),
            num_micro=2, optimizer=AdamW())
        resumed = repl.restore_checkpoint(str(tmp_path))
        resumed, _ = repl.train_step(resumed, x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)
