"""Ring attention / sequence parallelism / LM engine.

The decisive property: the sp-sharded path computes EXACTLY the same
function as the single-device path (ring attention is exact, not an
approximation), for values AND gradients, causal and not.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_ddp.models.transformer import TransformerLM, make_transformer
from tpu_ddp.parallel.mesh import DATA_AXIS, SEQ_AXIS, make_mesh
from tpu_ddp.parallel.ring_attention import full_attention, ring_attention
from tpu_ddp.train.lm import LMTrainer, make_lm_batch


def _qkv(key, b=2, L=32, h=4, d=16):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, L, h, d)) for k in ks)


def _ring_on_mesh(mesh, sp, causal):
    def fn(q, k, v):
        return ring_attention(q, k, v, SEQ_AXIS, sp, causal=causal)
    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, SEQ_AXIS), P(None, SEQ_AXIS), P(None, SEQ_AXIS)),
        out_specs=P(None, SEQ_AXIS), check_vma=False))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_full_attention(self, devices, causal, sp):
        q, k, v = _qkv(jax.random.key(0))
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        got = _ring_on_mesh(mesh, sp, causal)(q, k, v)
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match(self, devices):
        q, k, v = _qkv(jax.random.key(1), L=16)
        sp = 4
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        ring = _ring_on_mesh(mesh, sp, True)

        def loss_ring(q, k, v):
            return jnp.sum(ring(q, k, v) ** 2)

        def loss_full(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

        g_r = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_f = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_r, g_f):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-5)

    def test_causal_masks_future(self, devices):
        """Perturbing future positions must not change earlier outputs."""
        q, k, v = _qkv(jax.random.key(2), L=16)
        sp = 4
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        ring = _ring_on_mesh(mesh, sp, True)
        base = np.asarray(ring(q, k, v))
        k2 = k.at[:, 12:].add(100.0)
        v2 = v.at[:, 12:].add(-50.0)
        pert = np.asarray(ring(q, k2, v2))
        np.testing.assert_allclose(pert[:, :12], base[:, :12],
                                   rtol=1e-5, atol=1e-5)
        assert np.abs(pert[:, 12:] - base[:, 12:]).max() > 1e-3


class TestTransformerLM:
    def test_forward_shapes(self):
        model = make_transformer("TransformerLM-tiny", max_seq_len=64,
                                 compute_dtype=jnp.float32)
        params = model.init(jax.random.key(0))
        tokens = jnp.zeros((2, 64), jnp.int32)
        logits = model.apply(params, tokens)
        assert logits.shape == (2, 64, model.vocab_size)
        assert logits.dtype == jnp.float32

    def test_sp_sharded_matches_single_device(self, devices):
        """The whole MODEL (RoPE offsets + ring attention + loss path)
        computes the same function under sp=4 as on one device."""
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        params = model.init(jax.random.key(3))
        tokens = jax.random.randint(jax.random.key(4), (2, 32), 0, 1024)

        want = model.apply(params, tokens)

        sp = 4
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        sharded = model.with_sequence_parallel(SEQ_AXIS, sp)
        fn = jax.jit(jax.shard_map(
            sharded.apply, mesh=mesh,
            in_specs=(P(), P(None, SEQ_AXIS)),
            out_specs=P(None, SEQ_AXIS), check_vma=False))
        got = fn(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_causal_lm_property(self):
        """Changing token t+k must not change logits at positions < t."""
        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        params = model.init(jax.random.key(5))
        t = jax.random.randint(jax.random.key(6), (1, 16), 0, 1024)
        l1 = model.apply(params, t)
        t2 = t.at[0, 10].set((t[0, 10] + 7) % 1024)
        l2 = model.apply(params, t2)
        np.testing.assert_allclose(np.asarray(l1[:, :10]),
                                   np.asarray(l2[:, :10]),
                                   rtol=1e-5, atol=1e-5)


class TestAdamW:
    def test_three_layer_blocks_not_corrupted(self):
        """Regression: params trees containing 3-tuples (e.g. a 3-layer
        blocks tuple) must update structure-safely."""
        from tpu_ddp.ops.optim import AdamW
        model = make_transformer("TransformerLM-tiny", num_layers=3,
                                 max_seq_len=16,
                                 compute_dtype=jnp.float32)
        params = model.init(jax.random.key(0))
        grads = jax.tree.map(jnp.ones_like, params)
        opt = AdamW()
        state = opt.init(params)
        new_p, state = opt.apply(params, grads, state)
        assert jax.tree.structure(new_p) == jax.tree.structure(params)
        assert len(new_p["blocks"]) == 3
        for blk in new_p["blocks"]:
            assert set(blk) == {"ln1", "wqkv", "wo", "ln2", "w1", "w2"}
        # And the update actually moved every leaf.
        moved = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()),
                             params, new_p)
        assert min(jax.tree.leaves(moved)) > 0

    def test_matches_manual_single_step(self):
        from tpu_ddp.ops.optim import AdamW
        opt = AdamW(learning_rate=0.01, b1=0.9, b2=0.999, eps=1e-8,
                    weight_decay=0.0)
        p = {"w": jnp.asarray([2.0])}
        g = {"w": jnp.asarray([0.5])}
        state = opt.init(p)
        new_p, _ = opt.apply(p, g, state)
        mu = 0.1 * 0.5
        nu = 0.001 * 0.25
        step = (mu / 0.1) / (np.sqrt(nu / 0.001) + 1e-8)
        np.testing.assert_allclose(np.asarray(new_p["w"]),
                                   [2.0 - 0.01 * step], rtol=1e-6)


class TestLMTrainer:
    def test_train_step_dp_x_sp(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:8], dp=2, sp=4)
        tr = LMTrainer(model, mesh)
        assert tr.dp == 2 and tr.sp == 4
        state = tr.init_state()
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 1024, size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(3):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # AdamW memorizes a fixed batch fast
        assert state.step == 3

    def test_loss_matches_dp_only(self, devices):
        """First-step loss under dp=2 x sp=4 equals dp=8 x sp=1 equals
        the global token mean computed by hand."""
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        rng = np.random.default_rng(1)
        tokens = rng.integers(0, 1024, size=(8, 33))
        inp, tgt = make_lm_batch(tokens)

        def first_loss(dp, sp):
            mesh = make_mesh(devices[:8], dp=dp, sp=sp)
            tr = LMTrainer(model, mesh)
            state = tr.init_state(seed=42)
            x, y = tr.put_batch(inp, tgt)
            _, loss = tr.train_step(state, x, y)
            return float(np.mean(np.asarray(loss)))

        a = first_loss(2, 4)
        b = first_loss(8, 1)
        assert abs(a - b) < 1e-4, (a, b)

    def test_indivisible_raises(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        tr = LMTrainer(model, make_mesh(devices[:8], dp=2, sp=4))
        with pytest.raises(ValueError, match="not divisible"):
            tr.put_batch(np.zeros((3, 32), np.int32),
                         np.zeros((3, 32), np.int32))
        with pytest.raises(ValueError, match="not divisible"):
            tr.put_batch(np.zeros((2, 30), np.int32),
                         np.zeros((2, 30), np.int32))


class TestRemat:
    """remat_blocks recomputes activations in the backward pass without
    changing any value or gradient."""

    @pytest.mark.slow  # remat + dense fwd/bwd double compile; remat
    # identity is also pinned fast by test_vit's flash+remat check
    def test_values_and_grads_identical(self):
        dense = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        remat = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32,
                                 remat_blocks=True)
        params = dense.init(jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 1024)

        np.testing.assert_array_equal(
            np.asarray(dense.apply(params, tokens)),
            np.asarray(remat.apply(params, tokens)))

        def loss(model, p):
            return jnp.mean(model.apply(p, tokens) ** 2)

        g_d = jax.grad(lambda p: loss(dense, p))(params)
        g_r = jax.grad(lambda p: loss(remat, p))(params)
        for a, b in zip(jax.tree.leaves(g_d), jax.tree.leaves(g_r)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)

    # test_values_and_grads_identical pins remat-under-ring fast; the
    # pipeline composition re-tests two already-pinned pieces.
    @pytest.mark.slow
    def test_pipeline_with_remat(self, devices):
        """GPipe + per-layer remat trains and matches the dense step."""
        from tpu_ddp.ops.optim import SGD
        from tpu_ddp.train.lm import (LMTrainer, PipelineLMTrainer,
                                      make_lm_batch)

        sgd = SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)
        rng = np.random.default_rng(3)
        tokens = rng.integers(0, 1024, size=(4, 33))

        dense = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32, num_layers=4)
        tr0 = LMTrainer(dense, make_mesh(devices[:1], dp=1),
                        optimizer=sgd)
        s0 = tr0.init_state(seed=7)
        x0, y0 = tr0.put_batch(*make_lm_batch(tokens))
        s0, _ = tr0.train_step(s0, x0, y0)

        remat = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32, num_layers=4,
                                 remat_blocks=True)
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
        tr = PipelineLMTrainer(remat, mesh, num_micro=2, optimizer=sgd)
        s = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        s, _ = tr.train_step(s, x, y)

        from tpu_ddp.parallel.pipeline import unstack_block_params
        got = unstack_block_params(jax.device_get(s.params), 4)
        for a, b in zip(jax.tree.leaves(jax.device_get(s0.params)),
                        jax.tree.leaves(got)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)


class TestSchedules:
    def test_warmup_cosine_shape(self):
        from tpu_ddp.ops.optim import warmup_cosine

        s = warmup_cosine(1.0, warmup_steps=10, total_steps=100)
        assert abs(float(s(1.0)) - 0.1) < 1e-6        # warming up
        assert abs(float(s(10.0)) - 1.0) < 1e-6       # peak
        assert abs(float(s(55.0)) - 0.5) < 1e-6       # cosine midpoint
        assert abs(float(s(100.0)) - 0.0) < 1e-6      # decayed out
        assert abs(float(s(150.0)) - 0.0) < 1e-6      # clamped after end
        with pytest.raises(ValueError, match="warmup"):
            warmup_cosine(1.0, warmup_steps=0, total_steps=10)

    def test_scheduled_adamw_trains_and_resumes(self, devices, tmp_path):
        """The schedule reads the state's own count, so resume continues
        it exactly: save at step 2, restore, and step 3's update equals
        the uninterrupted run's."""
        from tpu_ddp.ops.optim import AdamW, warmup_cosine
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        opt = AdamW(learning_rate=warmup_cosine(3e-3, 2, 10))
        mesh = make_mesh(devices[:2], dp=2)
        tr = LMTrainer(model, mesh, optimizer=opt)
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(0).integers(0, 1024, size=(2, 17))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)

        restored = tr.restore_checkpoint(str(tmp_path))
        resumed, _ = tr.train_step(restored, x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)
