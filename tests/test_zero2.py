"""ZeRO-2: dp-scattered gradient accumulation (round-3 verdict item 5).

The rung between ZeRO-1 (optimizer-state sharding, part4) and ZeRO-3
(parameter sharding, part5): each microbatch's gradients are
reduce-scattered over dp IMMEDIATELY and the f32 accumulation buffer
holds 1/dp slices — accumulation memory drops ~dp x while the update
stays exactly the full-batch one. No reference counterpart (the
reference ladder stops at DDP, part3/main.py:174; ZeRO stages follow
arXiv:1910.02054 §5).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.optim import SGD, Adafactor
from tpu_ddp.parallel.mesh import DATA_AXIS, make_mesh
from tpu_ddp.train.lm import LMTrainer, make_lm_batch


def _model():
    return make_transformer("TransformerLM-tiny", max_seq_len=32,
                            compute_dtype=jnp.float32)


def _tokens(b=8, seed=5):
    return np.random.default_rng(seed).integers(0, 1024, size=(b, 33))


def _run(devices, opt_sharding, grad_accum=2, dp=2, sp=1, mp=1,
         steps=2, clip=None):
    # SGD: linear in the gradient, so scattered and dense accumulation
    # must agree to fp roundoff (the test_grad_accum.py rationale).
    mesh = make_mesh(devices[:dp * sp * mp], dp=dp, sp=sp, mp=mp)
    tr = LMTrainer(_model(), mesh, grad_accum=grad_accum,
                   opt_sharding=opt_sharding, clip_grad_norm=clip,
                   optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                 weight_decay=1e-4))
    state = tr.init_state(seed=21)
    x, y = tr.put_batch(*make_lm_batch(_tokens()))
    losses = []
    for _ in range(steps):
        state, loss = tr.train_step(state, x, y)
        losses.append(float(np.mean(np.asarray(loss))))
    return tr, jax.device_get(state.params), losses


class TestZeRO2:
    def test_matches_replicated_and_zero1(self, devices):
        """Two accumulated steps: zero2 == zero1 == replicated (same
        losses AND same final params; step 2 runs momentum through the
        scattered layout)."""
        runs = {s: _run(devices, s) for s in ("replicated", "zero1",
                                              "zero2")}
        for s in ("zero1", "zero2"):
            np.testing.assert_allclose(runs[s][2], runs["replicated"][2],
                                       rtol=1e-5, err_msg=s)
            for a, b in zip(jax.tree.leaves(runs["replicated"][1]),
                            jax.tree.leaves(runs[s][1])):
                np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                           rtol=2e-5, atol=1e-6,
                                           err_msg=s)

    def test_matches_without_accumulation(self, devices):
        """grad_accum=1 degenerates to zero1 (scatter before the non-dp
        sync commutes with it)."""
        _, p_z1, l_z1 = _run(devices, "zero1", grad_accum=1)
        _, p_z2, l_z2 = _run(devices, "zero2", grad_accum=1)
        np.testing.assert_allclose(l_z2, l_z1, rtol=1e-6)
        for a, b in zip(jax.tree.leaves(p_z1), jax.tree.leaves(p_z2)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-6, atol=1e-7)

    def test_composes_with_tp(self, devices):
        """dp2 x tp2: the scattered accumulation rides the partition-
        aware ZeRO layout (slices are per model-parallel cell)."""
        _, p_ref, l_ref = _run(devices, "replicated", mp=2)
        _, p_z2, l_z2 = _run(devices, "zero2", mp=2)
        np.testing.assert_allclose(l_z2, l_ref, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_z2)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-5, atol=1e-6)

    @pytest.mark.slow  # zero2 x sp adds only layout on the gather the
    # replicated/zero1 parity above pins fast.
    def test_composes_with_sp(self, devices):
        """dp2 x sp2: the non-dp sync applies elementwise to slices."""
        _, p_ref, l_ref = _run(devices, "replicated", dp=2, sp=2)
        _, p_z2, l_z2 = _run(devices, "zero2", dp=2, sp=2)
        np.testing.assert_allclose(l_z2, l_ref, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_z2)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-5, atol=1e-6)

    def test_state_layout_is_zero1(self, devices):
        """ZeRO-2 keeps ZeRO-1's sharded optimizer-state layout (the
        stage adds gradient sharding, not a new state layout)."""
        tr, _, _ = _run(devices, "zero2", dp=2, steps=1)
        state = tr.init_state(seed=0)
        mom = state.opt_state["momentum"]
        leaf = jax.tree.leaves(mom)[0]
        assert leaf.ndim == 1
        assert leaf.sharding.spec == P(DATA_AXIS)

    @pytest.mark.slow  # compiles two grad_accum=4 programs just for
    # memory_analysis; scripts/zero2_memory.py records the same claim
    def test_accumulation_buffer_is_sharded(self, devices):
        """The compiled step's live-memory accounting must show the win:
        the zero2 program's peak temp allocation is SMALLER than zero1's
        (the A-microbatch f32 buffer holds 1/dp slices instead of full
        leaves). XLA:CPU supports memory_analysis; skip if not."""
        mesh = make_mesh(devices[:2], dp=2)

        def compiled_peak(sharding):
            tr = LMTrainer(_model(), mesh, grad_accum=4,
                           opt_sharding=sharding,
                           optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                         weight_decay=1e-4))
            state = tr.init_state(seed=0)
            x, y = tr.put_batch(*make_lm_batch(_tokens()))
            lowered = tr._train_step.lower(state.params, state.opt_state,
                                           x, y, *tr._extra_args(state))
            compiled = lowered.compile()  # a compile FAILURE must fail
            try:
                mem = compiled.memory_analysis()
                return int(mem.temp_size_in_bytes)
            except Exception:
                pytest.skip("backend exposes no memory analysis")

        z1, z2 = compiled_peak("zero1"), compiled_peak("zero2")
        assert z2 < z1, (z1, z2)

    def test_adafactor_refused(self, devices):
        mesh = make_mesh(devices[:2], dp=2)
        with pytest.raises(ValueError, match="zero2"):
            LMTrainer(_model(), mesh, opt_sharding="zero2",
                      optimizer=Adafactor(min_dim_size_to_factor=8))

    def test_checkpoint_into_replicated(self, devices, tmp_path):
        """zero2 checkpoints are canonical (same path as zero1)."""
        tr, _, _ = _run(devices, "zero2", steps=1)
        state = tr.init_state(seed=21)
        x, y = tr.put_batch(*make_lm_batch(_tokens()))
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)

        repl = LMTrainer(_model(), make_mesh(jax.devices()[:2], dp=2),
                         grad_accum=2,
                         optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                       weight_decay=1e-4))
        resumed = repl.restore_checkpoint(str(tmp_path))
        resumed, _ = repl.train_step(resumed, x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)


class TestZeRO2Pipeline:
    """ZeRO-2 under the 1F1B pipeline (round-4 verdict item 5): each
    tick's block-gradient contribution is reduce-scattered over dp
    inside the scan, so the accumulation carry holds 1/dp f32 slices —
    num_micro IS the accumulation regime ZeRO-2 exists for."""

    def _run_pp(self, devices, opt_sharding, clip=None, mp=1, steps=2,
                num_micro=4):
        from tpu_ddp.train.lm import PipelineLMTrainer
        mesh = make_mesh(devices[:4 * mp], dp=2, pp=2, mp=mp)
        tr = PipelineLMTrainer(
            _model(), mesh, num_micro=num_micro, schedule="1f1b",
            opt_sharding=opt_sharding, clip_grad_norm=clip,
            optimizer=SGD(learning_rate=0.1, momentum=0.9,
                          weight_decay=1e-4))
        state = tr.init_state(seed=21)
        x, y = tr.put_batch(*make_lm_batch(_tokens()))
        losses = []
        for _ in range(steps):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        return tr, jax.device_get(state.params), losses

    def test_matches_zero1(self, devices):
        """Per-tick scattered accumulation == zero1's scatter-at-the-end
        (SGD: linear in the gradient, so fp roundoff only)."""
        _, p1, l1 = self._run_pp(devices, "zero1")
        _, p2, l2 = self._run_pp(devices, "zero2")
        np.testing.assert_allclose(l2, l1, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-5, atol=1e-6)

    @pytest.mark.slow  # two 8-device 1f1b compiles; the bare zero2-pp
    # exactness runs fast above, this pins the x tp x clip frontier
    def test_matches_zero1_with_clip_and_tp(self, devices):
        """Global-norm clip on the mixed slice tree + stage-internal tp
        (P((pp, mp, dp)) state): still exactly zero1."""
        _, p1, l1 = self._run_pp(devices, "zero1", clip=0.5, mp=2)
        _, p2, l2 = self._run_pp(devices, "zero2", clip=0.5, mp=2)
        np.testing.assert_allclose(l2, l1, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-5, atol=1e-6)

    def test_gpipe_refused(self, devices):
        """GPipe differentiates the whole tick scan at once — no
        per-microbatch accumulator exists to scatter, so the combination
        is refused loudly rather than silently running as zero1."""
        from tpu_ddp.train.lm import PipelineLMTrainer
        mesh = make_mesh(devices[:4], dp=2, pp=2)
        with pytest.raises(ValueError, match="1f1b"):
            PipelineLMTrainer(_model(), mesh, num_micro=4,
                              schedule="gpipe", opt_sharding="zero2")

    @pytest.mark.slow  # two 1f1b compiles just for memory_analysis;
    # scripts/zero2_memory.py records the same claim as an artifact
    def test_accumulation_carry_is_sharded(self, devices):
        """XLA's live-memory accounting must show the win: the zero2
        program's peak temp allocation is smaller than zero1's (the
        1F1B scan carry holds 1/dp block-gradient slices)."""
        import pytest as _pytest
        from tpu_ddp.train.lm import PipelineLMTrainer
        mesh = make_mesh(devices[:2], dp=2, pp=1)

        def compiled_peak(sharding):
            tr = PipelineLMTrainer(
                _model(), mesh, num_micro=4, schedule="1f1b",
                opt_sharding=sharding,
                optimizer=SGD(learning_rate=0.1, momentum=0.9,
                              weight_decay=1e-4))
            state = tr.init_state(seed=0)
            x, y = tr.put_batch(*make_lm_batch(_tokens()))
            lowered = tr._train_step.lower(state.params, state.opt_state,
                                           x, y, *tr._extra_args(state))
            compiled = lowered.compile()  # a compile FAILURE must fail
            try:
                mem = compiled.memory_analysis()
                return int(mem.temp_size_in_bytes)
            except Exception:
                _pytest.skip("backend exposes no memory analysis")

        z1, z2 = compiled_peak("zero1"), compiled_peak("zero2")
        assert z2 < z1, (z1, z2)
