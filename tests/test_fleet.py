"""The serving fleet (tpu_ddp/fleet/): refcounted prefix caching over
the paged pool, prefill/decode disaggregation over the KV edge, and the
multi-replica router (docs/DESIGN.md §21).

The acceptance bar everything here leans on is BITWISE TOKEN PARITY:
same seed and request set in, identical tokens out — whether requests
run through one engine, a prefix-cached engine, a disaggregated
prefill/decode pair (``kv_wire="none"``), or a routed fleet. Sampling
is stateless-keyed by (seed, position) and the decode math has exactly
one implementation (``serve/engine.decode_bank``), so any divergence is
a real bug in block bookkeeping, not float noise.

Geometry matches tests/test_serve.py (block_size=8, num_slots=4 at
max_seq_len=64), so the single-engine step programs are shared; the
fused adopt+decode program adds one compile per distinct transfer
block-count.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.fleet import DisaggEngine, KVEdge, PrefixIndex, Router
from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.serve import (
    PagedKVPool,
    ServeEngine,
    make_shared_prefix_workload,
    run_load,
)

GEOM = dict(num_slots=4, block_size=8, prefill_chunk=8)


@pytest.fixture(scope="module")
def model():
    return make_transformer("TransformerLM-tiny", max_seq_len=64,
                            compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def _prompt(L, seed=0):
    return np.random.default_rng(seed).integers(0, 1024, size=L,
                                                dtype=np.int64)


def _serve_all(engine, cases, seed0=0):
    """Submit (prompt_seed, L, n, temp) cases, run to idle, return the
    per-request token lists."""
    hs = [engine.submit(_prompt(L, seed=ps), n, temperature=t, seed=i)
          for i, (ps, L, n, t) in enumerate(cases, start=seed0)]
    engine.run()
    assert all(h.done for h in hs)
    return [h.tokens for h in hs]


MIXED = [(0, 5, 6, 0.0), (1, 9, 5, 0.0), (2, 12, 4, 0.7),
         (3, 8, 6, 1.0)]


class TestRefcounts:
    def test_share_free_lifecycle_and_identity(self, model):
        pool = PagedKVPool(model, num_blocks=6, block_size=8)
        b = pool.alloc()
        assert pool.refcount(b) == 1
        pool.incref([b])
        pool.incref([b])
        assert pool.refcount(b) == 3
        pool.free([b])                   # decref, still held
        pool.free([b])
        assert pool.refcount(b) == 1 and pool.free_count == 4
        pool.free([b])                   # last holder: page returns
        assert pool.refcount(b) == 0 and pool.free_count == 5
        # §21 identity: free + unique-allocated == total usable.
        a, c = pool.alloc(), pool.alloc()
        pool.incref([a])
        assert pool.refcount_ok([[a, c], [a]])
        assert not pool.refcount_ok([[a, c]])     # missing a holder
        assert not pool.refcount_ok([[a, c], [a], [c]])  # phantom

    def test_refcount_never_negative(self, model):
        pool = PagedKVPool(model, num_blocks=4, block_size=8)
        b = pool.alloc()
        pool.free([b])
        with pytest.raises(ValueError, match="double free"):
            pool.free([b])
        assert pool.refcount(b) == 0     # clamped by the raise
        with pytest.raises(ValueError, match="unallocated"):
            pool.incref([b])             # can't resurrect a free page
        with pytest.raises(ValueError, match="null block"):
            pool.incref([PagedKVPool.NULL_BLOCK])

    def test_cow_copies_content_into_private_block(self, model):
        pool = PagedKVPool(model, num_blocks=4, block_size=8)
        b = pool.alloc()
        pool.commit(pool.k.at[:, b].set(7.0), pool.v.at[:, b].set(3.0))
        pool.incref([b])
        c = pool.cow(b)
        assert c != b and pool.refcount(c) == 1
        np.testing.assert_array_equal(np.asarray(pool.k[:, c]),
                                      np.asarray(pool.k[:, b]))
        np.testing.assert_array_equal(np.asarray(pool.v[:, c]),
                                      np.asarray(pool.v[:, b]))
        # Writing the copy leaves the shared original untouched.
        pool.commit(pool.k.at[:, c].set(9.0), pool.v)
        assert float(pool.k[0, b, 0, 0, 0]) == 7.0


class TestPrefixIndex:
    def test_chain_keys_are_exact_and_plan_is_pure(self, model):
        pool = PagedKVPool(model, num_blocks=8, block_size=8)
        idx = PrefixIndex(pool)
        p = _prompt(16, seed=1)
        blocks = [pool.alloc(), pool.alloc()]
        idx.register(p, blocks)
        assert idx.stats()["entries"] == 2
        hit = idx.plan(p)
        assert hit.blocks == blocks
        assert hit.cached_len == 15      # final token always re-runs
        assert hit.cow                   # block-aligned full match
        # One shared token-block prefix, divergent second block.
        q = np.concatenate([p[:8], _prompt(8, seed=2)])
        h2 = idx.plan(q)
        assert h2.blocks == blocks[:1] and h2.cached_len == 8
        assert not h2.cow
        # A token flip in the FIRST block kills the whole chain.
        r = p.copy()
        r[0] = (r[0] + 1) % 1024
        assert not idx.plan(r)
        # plan() took no refcounts and no stats.
        assert pool.refcount(blocks[0]) == 2  # slot + index only
        assert idx.lookups == 0

    def test_reclaim_is_lru_leaf_first_with_cascade(self, model):
        pool = PagedKVPool(model, num_blocks=8, block_size=8)
        idx = PrefixIndex(pool)
        pa = _prompt(16, seed=3)
        ba = [pool.alloc(), pool.alloc()]
        idx.register(pa, ba)
        pool.free(ba)                    # index is now the only holder
        assert idx.evictable_count == 1  # leaf only (conservative)
        assert pool.allocatable == pool.free_count + 1
        # A dry pool reclaims THROUGH the index: leaf, then its parent.
        got = [pool.alloc() for _ in range(pool.free_count + 2)]
        assert len(set(got)) == len(got)
        assert idx.stats()["entries"] == 0 and idx.evicted == 2
        with pytest.raises(RuntimeError, match="exhausted"):
            pool.alloc()

    def test_shared_prompt_parity_and_stats(self, model, params):
        """N requests sharing a 16-token system prompt through a
        prefix-cached engine: tokens bitwise-equal the uncached
        engine's, the shared blocks are prefilled ONCE, and the
        accounting identity holds after the drill."""
        system = _prompt(16, seed=4)
        tails = [_prompt(4, seed=10 + i) for i in range(3)]
        prompts = [np.concatenate([system, t]) for t in tails]
        plain = ServeEngine(model, params, **GEOM)
        cached = ServeEngine(model, params, prefix_cache=True, **GEOM)
        want, got = [], []
        for i, p in enumerate(prompts):
            a = plain.submit(p, 5, seed=i)
            plain.run()
            b = cached.submit(p, 5, seed=i)
            cached.run()
            want.append(a.tokens)
            got.append(b.tokens)
        assert got == want
        st = cached.prefix.stats()
        assert st["hit_requests"] == 2          # first pays, rest hit
        assert st["tokens_saved"] == 2 * 16
        assert cached.sched.accounting_ok()

    def test_cow_divergence_is_bitwise_private(self, model, params):
        """Two IDENTICAL block-aligned prompts: the second adopts every
        prompt block and re-runs only the final token into a CoW copy.
        Its tokens must equal the uncached engine's bitwise, and the
        original cached block must stay pristine for a third hit."""
        p = _prompt(16, seed=5)
        plain = ServeEngine(model, params, **GEOM)
        cached = ServeEngine(model, params, prefix_cache=True, **GEOM)
        want = []
        for i in range(3):
            h = plain.submit(p, 5, temperature=0.5, seed=i)
            plain.run()
            want.append(h.tokens)
        got = []
        for i in range(3):
            h = cached.submit(p, 5, temperature=0.5, seed=i)
            cached.run()
            got.append(h.tokens)
        assert got == want
        st = cached.prefix.stats()
        assert st["hit_requests"] == 2
        assert cached.sched.accounting_ok()


class TestDisagg:
    def test_bitwise_parity_with_single_engine(self, model, params):
        single = ServeEngine(model, params, **GEOM)
        fleet = DisaggEngine(model, params, kv_wire="none", **GEOM)
        assert _serve_all(fleet, MIXED) == _serve_all(single, MIXED)
        # Both roles drain completely.
        assert fleet.pool.free_count == fleet.pool.total_usable
        assert fleet.prefill_pool.free_count \
            == fleet.prefill_pool.total_usable
        assert fleet.accounting_ok()
        assert fleet.edge.stats()["sent"] \
            == fleet.edge.stats()["delivered"] == len(MIXED)

    def test_parity_with_prefix_cache_on(self, model, params):
        system = _prompt(16, seed=6)
        prompts = [np.concatenate([system, _prompt(3, seed=20 + i)])
                   for i in range(3)]
        single = ServeEngine(model, params, **GEOM)
        fleet = DisaggEngine(model, params, kv_wire="none",
                             prefix_cache=True, **GEOM)
        want, got = [], []
        for i, p in enumerate(prompts):
            a = single.submit(p, 4, seed=i)
            single.run()
            b = fleet.submit(p, 4, seed=i)
            fleet.run()
            want.append(a.tokens)
            got.append(b.tokens)
        assert got == want
        assert fleet.prefix.stats()["hit_requests"] == 2
        assert fleet.accounting_ok()

    @pytest.mark.parametrize("wire,min_ratio", [("bf16", 1.9),
                                                ("int8", 3.0)])
    def test_lossy_wires_complete_and_compress(self, model, params,
                                               wire, min_ratio):
        fleet = DisaggEngine(model, params, kv_wire=wire, **GEOM)
        hs = [fleet.submit(_prompt(9, seed=30 + i), 5)
              for i in range(2)]
        fleet.run()
        assert all(h.done and len(h.tokens) == 5 for h in hs)
        st = fleet.edge.stats()
        assert st["ratio"] >= min_ratio  # honest byte accounting
        assert fleet.pool.free_count == fleet.pool.total_usable

    def test_wire_validation(self):
        with pytest.raises(ValueError, match="kv_wire"):
            KVEdge("fp4")

    def test_transfer_lands_behind_decode_compute(self, model, params):
        """The overlap claim, checked on compiled HLO: the fused
        adopt+decode program's landing scatters have NO heavy ancestor
        (the transfer can start at step begin) and heavy decode ops
        outside their cones to hide behind."""
        from tpu_ddp.utils.hlo_comm import (
            assert_transfer_overlap,
            update_overlap_report,
        )
        fleet = DisaggEngine(model, params, **GEOM)
        rep = assert_transfer_overlap(fleet.adopt_decode_hlo(2))
        assert rep["n_updates"] >= 2     # k and v landings
        assert all(u["n_heavy_ancestors"] == 0 for u in rep["updates"])
        # Negative control: the same math with the adoption applied
        # AFTER the decode bank serializes the landing behind every
        # heavy op feeding the pool — the analysis must say NO.
        import functools

        from tpu_ddp.serve.engine import decode_bank

        def bad_step(params, pool_k, pool_v, adopt_ids, adopt_k,
                     adopt_v, tables, lengths, last_tokens, temps,
                     seeds):
            k, v, toks, lps, _bad = decode_bank(
                model, fleet.block_size, fleet.blocks_per_seq, params,
                pool_k, pool_v, tables, lengths, last_tokens, temps,
                seeds)
            k = k.at[:, adopt_ids].set(adopt_k.astype(k.dtype))
            v = v.at[:, adopt_ids].set(adopt_v.astype(v.dtype))
            return k, v, toks, lps

        fn = jax.jit(bad_step, donate_argnums=(1, 2))
        sds = jax.ShapeDtypeStruct
        spec = jax.tree.map(lambda x: sds(jnp.shape(x),
                                          jnp.result_type(x)),
                            fleet.params)
        S, BPS = fleet.num_slots, fleet.blocks_per_seq
        pk = sds(fleet.pool.k.shape, fleet.pool.k.dtype)
        pay = sds((model.num_layers, 2, fleet.block_size,
                   model.kv_heads, model.head_dim), jnp.float32)
        i32 = functools.partial(sds, dtype=jnp.int32)
        bad = fn.lower(spec, pk, pk, i32((2,)), pay, pay,
                       i32((S, BPS)), i32((S,)), i32((S,)),
                       sds((S,), jnp.float32),
                       i32((S,))).compile().as_text()
        brep = update_overlap_report(bad)
        assert not brep["overlapped"]
        assert all(u["n_heavy_ancestors"] > 0 for u in brep["updates"])
        with pytest.raises(AssertionError, match="not overlappable"):
            assert_transfer_overlap(bad)


class TestRouter:
    def test_validation_and_least_loaded_balance(self, model, params):
        with pytest.raises(ValueError, match="at least one"):
            Router([])
        with pytest.raises(ValueError, match="policy"):
            Router([ServeEngine(model, params, **GEOM)], policy="rr")
        r = Router([ServeEngine(model, params, **GEOM)
                    for _ in range(2)], policy="least-loaded")
        for i in range(6):
            r.submit(_prompt(6, seed=40 + i), 4, seed=i)
        assert r.routed == [3, 3]        # alternating under equal load
        r.run()
        assert r.accounting_ok() and r.outstanding() == 0

    def test_routed_fleet_matches_single_engine_tokens(self, model,
                                                       params):
        single = ServeEngine(model, params, **GEOM)
        want = _serve_all(single, MIXED)
        r = Router([ServeEngine(model, params, prefix_cache=True,
                                **GEOM) for _ in range(2)],
                   policy="prefix-affinity")
        got = _serve_all(r, MIXED)
        assert got == want               # parity survives routing

    def test_prefix_affinity_beats_least_loaded_hit_rate(self, model,
                                                         params):
        """The policy's reason to exist: shared-prompt traffic piled
        onto the replica that already paid the prefill. Deterministic
        pacing (placement, not timing, is under test): one warm-up
        request drained alone, then PAIRS submitted together so
        least-loaded must spread each pair — its second stream pays
        the shared prefill again on the cold replica."""
        def fleet(policy):
            return Router([ServeEngine(model, params,
                                       prefix_cache=True, **GEOM)
                           for _ in range(2)], policy=policy)

        def hit_rate(router, specs):
            router.submit(specs[0].prompt, specs[0].max_new_tokens,
                          seed=specs[0].seed)
            router.run()
            for a, b in zip(specs[1::2], specs[2::2]):
                for sp in (a, b):        # concurrent pair
                    router.submit(sp.prompt, sp.max_new_tokens,
                                  seed=sp.seed)
                router.run()
            st = [rep["prefix"] for rep in
                  router.stats()["replicas"]]
            return (sum(s["hit_requests"] for s in st)
                    / sum(s["lookups"] for s in st))

        specs = make_shared_prefix_workload(9, model.vocab_size,
                                            seed=7, prefix_len=16)
        aff, ll = fleet("prefix-affinity"), fleet("least-loaded")
        r_aff, r_ll = hit_rate(aff, specs), hit_rate(ll, specs)
        assert r_aff == 8 / 9            # one cold miss total
        assert r_ll == 7 / 9             # one cold miss PER replica
        assert r_aff > r_ll
        assert aff.affinity_hits == 8
        # Affinity concentrated the stream; least-loaded split it.
        assert sorted(aff.routed) == [0, 9]
        assert sorted(ll.routed) == [4, 5]

    def test_affinity_slack_caps_hot_replica_pileup(self, model,
                                                    params):
        r = Router([ServeEngine(model, params, prefix_cache=True,
                                **GEOM) for _ in range(2)],
                   policy="prefix-affinity", affinity_slack=0)
        p = _prompt(20, seed=8)
        r.submit(p, 8)
        r.run()                          # replica 0 caches the prompt
        r.submit(p, 8)                   # backlog 0 vs 0: affinity OK
        assert r.routed[0] == 2
        # Replica 0 now owes work; slack 0 forces the next one over.
        i = r.pick(p)
        assert i == 1
        r.run()

    @pytest.mark.slow  # wall-clock fleet drill (~30-60 s)
    def test_two_replica_fleet_no_leak_drill(self, model, params):
        """The §21 acceptance drill: a 2-replica disagg+prefix fleet
        under a shared-prefix open-system load, accounting checked at
        the end on every pool in the fleet — nothing leaks, nothing
        double-frees, and the run produces full-length generations."""
        replicas = [DisaggEngine(model, params, kv_wire="bf16",
                                 prefix_cache=True, **GEOM)
                    for _ in range(2)]
        router = Router(replicas, policy="prefix-affinity")
        specs = make_shared_prefix_workload(
            40, model.vocab_size, seed=9, prefix_len=16,
            tail_len=(2, 9), max_new=(3, 9))
        m = run_load(router, specs, rate=100.0, seed=9)
        assert m["n_requests"] == 40
        assert m["total_tokens"] == sum(s.max_new_tokens
                                        for s in specs)
        assert m["tpot_p99_ms"] is not None
        assert router.accounting_ok()
        for rep in replicas:
            assert rep.pool.free_count == rep.pool.total_usable
            held = len(rep.prefix.held_blocks())
            assert rep.prefill_pool.free_count + held \
                == rep.prefill_pool.total_usable
        assert sum(router.routed) == 40
