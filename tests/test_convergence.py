"""Convergence acceptance: every ladder rung LEARNS.

The reference validates learning statistically (loss/accuracy after one
epoch, report Table 1 — quoted in BASELINE.md); the full-epoch analogue
here is the committed artifact experiments/results_convergence.json
(produced on the real chip by scripts/run_experiments.py). This test is
the CI-sized guard: a short run on the class-conditional synthetic
stand-in must push the training loss well below its ~2.3 starting point,
and the rungs must agree with each other — a regression in any rung's
update math shows up as a loss that stays put or diverges from the
others.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.data.cifar10 import load_cifar10, normalize
from tpu_ddp.models.vgg import VGGModel
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig


def _batches(n_iters=12, bs=16):
    images, labels, meta = load_cifar10(split="train",
                                        synthetic_size=n_iters * bs)
    if not meta["synthetic"]:
        # The thresholds target the separable synthetic stand-in; on a
        # box with real CIFAR-10 discoverable this tier defers to the
        # full-epoch report (scripts/run_experiments.py).
        pytest.skip("real CIFAR-10 present; thresholds are for the "
                    "synthetic stand-in")
    x = normalize(images)
    return [(x[i * bs:(i + 1) * bs], labels[i * bs:(i + 1) * bs])
            for i in range(n_iters)]


def _final_window_loss(trainer, batches):
    state = trainer.init_state()
    losses = []
    for bx, by in batches:
        state, loss = trainer.train_step(state, *trainer.put_batch(bx, by))
        losses.append(float(np.mean(np.asarray(loss))))
    assert all(np.isfinite(losses)), losses
    return float(np.mean(losses[-3:]))


@pytest.mark.slow
@pytest.mark.parametrize("strategy", ["none", "gather_scatter",
                                      "all_reduce", "fused", "zero",
                                      "fsdp"])
def test_rung_loss_falls(devices, strategy):
    # A slimmer VGG plan keeps this CPU-affordable while exercising the
    # real conv/BN/pool stack and every sync strategy's update math.
    model = VGGModel(name="slim", cfg=(8, "M", 8, "M", 16, "M", 16, "M", 32, "M"),
                     compute_dtype=jnp.float32)
    mesh = None if strategy == "none" else make_mesh(devices[:2])
    trainer = Trainer(model, TrainConfig(), strategy=strategy, mesh=mesh)
    final = _final_window_loss(trainer, _batches())
    # Start is ~ln(10)=2.3 (and the first augmented iterations overshoot
    # it); a no-learning regression hovers there, while a healthy run
    # reaches ~1.9 within 12 iterations on the 2-device mesh.
    assert final < 2.0, f"{strategy}: final-window loss {final:.3f}"


@pytest.mark.slow
def test_rungs_agree(devices):
    """The distributed rungs share exact update math at a fixed world
    size — their loss trajectories must coincide tightly."""
    model = VGGModel(name="slim", cfg=(8, "M", 8, "M", 16, "M", 16, "M", 32, "M"),
                     compute_dtype=jnp.float32)
    batches = _batches()
    finals = {}
    for strategy in ("all_reduce", "fused", "zero", "fsdp"):
        trainer = Trainer(model, TrainConfig(), strategy=strategy,
                          mesh=make_mesh(devices[:2]))
        finals[strategy] = _final_window_loss(trainer, batches)
    spread = max(finals.values()) - min(finals.values())
    assert spread < 1e-2, finals
