"""Speculative decoding + weight-only int8 decode (DESIGN.md §26).

What this file pins, by class:

- **Accept rule** — ``accept_length`` math in isolation (greedy and
  adversarial prefixes), and the engine-level ledger identity
  ``proposed == accepted + rejected`` per request and in aggregate,
  for both fused draft families.
- **Chain parity** — the tentpole exactness claim: a ``spec_draft=
  "chain"`` engine emits token AND logprob streams bitwise identical
  to the k=0 engine, because every sample comes from the same
  compiled decode program. Pinned across k values, rebatching,
  replica-crash migration, weight hot-swap and the int8 family.
- **KV rollback** — the fused families' pool invariant: rejection
  returns tail blocks via ``trim_blocks`` and
  ``free + Σallocated == total`` holds after EVERY step, fuzzed over
  seeded workloads at temperature 1.0 (low acceptance, max churn).
- **Quantizer** — per-channel int8 error bounds, the 0.25%-of-fp32
  NLL quality bar, fp-path bitwise neutrality of ``qdot``, and the
  Pallas kernel vs the XLA reference contraction.
- **Knobs** — the four-surface convention for TPU_DDP_SPEC_K /
  TPU_DDP_SPEC_DRAFT / TPU_DDP_DECODE_QUANT: env flow into the
  engine, junk rejection at config, coupled-knob violations at the
  engine door.
- **TPOT bugfix** — loadgen inter-token percentiles come from the
  per-token emission stamps (``Request.token_times``), not the old
  uniform (finished-first)/(n-1) estimate that averaged speculative
  bursts away.

Engines here share test_serve's cache geometry (block_size=8,
blocks_per_seq=8 at max_seq_len=64) so the fast tier reuses the same
memoized decode/prefill programs; only the fused spec-step programs
(one per (k, draft_layers, treedef)) compile anew.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.fleet import ReplicaCrashError, Router
from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.quant import (
    QuantizedWeight,
    dequantize,
    nll_drift,
    qdot,
    quantize_params,
    quantize_weight,
)
from tpu_ddp.serve import Request, ServeEngine, run_load
from tpu_ddp.serve.loadgen import RequestSpec
from tpu_ddp.serve.speculative import (
    SPEC_DRAFTS,
    accept_length,
    parse_spec_draft,
)

GEOM = dict(num_slots=4, block_size=8, prefill_chunk=8)

# Mixed greedy/sampled workload: (prompt_seed, prompt_len, max_new,
# temperature) — the parity reference covers both sampling regimes.
MIXED = [(0, 5, 6, 0.0), (1, 9, 5, 0.0), (2, 12, 4, 0.7),
         (3, 8, 6, 1.0)]


@pytest.fixture(scope="module")
def model():
    return make_transformer("TransformerLM-tiny", max_seq_len=64,
                            compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


@pytest.fixture(scope="module")
def baseline(model, params):
    """The k=0 engine's (token, logprob) streams for MIXED — the
    bitwise reference every chain cell is judged against."""
    eng = ServeEngine(model, params, **GEOM)
    hs = _submit_mixed(eng)
    eng.run()
    return _streams(hs)


def _prompt(L, seed=0):
    return np.random.default_rng(seed).integers(0, 1024, size=L,
                                                dtype=np.int64)


def _submit_mixed(engine):
    return [engine.submit(_prompt(L, seed=ps), n, temperature=t, seed=i)
            for i, (ps, L, n, t) in enumerate(MIXED)]


def _streams(handles):
    return [(list(h.tokens), list(h.logprobs)) for h in handles]


def _ledger_ok(engine, handles) -> bool:
    st = engine.spec_stats()
    return (st["proposed"] == st["accepted"] + st["rejected"]
            and all(h.spec_proposed == h.spec_accepted + h.spec_rejected
                    for h in handles))


# ---------------------------------------------------------------------------
# The accept rule
# ---------------------------------------------------------------------------

class TestAcceptRule:
    def test_full_match_accepts_all(self):
        assert accept_length([5, 6, 7], [5, 6, 7, 9], 3) == 3

    def test_first_mismatch_truncates(self):
        # Draft guessed position 0 wrong: zero proposals accepted,
        # but the engine still emits target column 0 (the token the
        # non-speculative step would have produced).
        assert accept_length([4, 6, 7], [5, 6, 7, 9], 3) == 0

    def test_mismatch_mid_prefix(self):
        assert accept_length([5, 8, 7], [5, 6, 7, 9], 3) == 1

    def test_late_match_does_not_resurrect(self):
        # A correct guess AFTER a wrong one is unusable: the verify
        # column consumed the wrong input, so the prefix rule must
        # not skip over the gap.
        assert accept_length([5, 8, 9], [5, 6, 9, 9], 3) == 1

    @pytest.mark.parametrize("knobs", [
        dict(spec_k=3, spec_draft="self-1"),
        # The quant family's ledger runs the same accept path; its
        # accounting stays in the fast tier via TestKVRollback.
        pytest.param(dict(spec_k=3, spec_draft="quant",
                          decode_quant="int8"),
                     marks=pytest.mark.slow),
    ])
    def test_fused_ledger_identity(self, model, params, knobs):
        """proposed == accepted + rejected, per request and in
        aggregate, and every request still gets its full budget —
        acceptance changes THROUGHPUT, never the emitted stream
        length."""
        eng = ServeEngine(model, params, **GEOM, **knobs)
        hs = _submit_mixed(eng)
        eng.run()
        assert all(h.done for h in hs)
        assert all(len(h.tokens) == n for h, (_, _, n, _) in
                   zip(hs, MIXED))
        assert _ledger_ok(eng, hs)
        st = eng.spec_stats()
        assert st["proposed"] > 0
        assert st["acceptance"] == pytest.approx(
            st["accepted"] / st["proposed"])

    def test_chain_accepts_everything_by_construction(self, model,
                                                      params):
        """The chain schedule has no separate draft to disagree with:
        every proposal beyond column 0 is an accepted target sample,
        so rejected == 0 unless a request finishes mid-window."""
        eng = ServeEngine(model, params, **GEOM, spec_k=3)
        h = eng.submit(_prompt(6, seed=9), 8, temperature=1.0, seed=4)
        eng.run()
        assert h.spec_rejected == 0
        assert h.spec_proposed == h.spec_accepted
        assert _ledger_ok(eng, [h])


# ---------------------------------------------------------------------------
# Chain bitwise parity — the exactness tentpole
# ---------------------------------------------------------------------------

class TestChainParity:
    @pytest.mark.parametrize("k", [1, 3, 7])
    def test_bitwise_parity_vs_k0(self, model, params, baseline, k):
        """Token AND logprob streams equal the k=0 engine's bitwise,
        greedy and sampled alike — the structural claim spec_sweep
        enforces on every committed chain cell."""
        eng = ServeEngine(model, params, **GEOM, spec_k=k)
        hs = _submit_mixed(eng)
        eng.run()
        assert _streams(hs) == baseline
        assert eng.accounting_ok()
        assert _ledger_ok(eng, hs)

    def test_parity_survives_rebatching(self, model, params):
        """The stateless fold_in(seed, position) keys make a request's
        stream independent of its batch neighbors — with speculation
        ALSO independent of which window column a position lands in."""
        prompt = _prompt(6, seed=50)
        alone = ServeEngine(model, params, **GEOM, spec_k=4)
        r1 = alone.submit(prompt, 6, temperature=1.0, seed=7)
        alone.run()
        crowded = ServeEngine(model, params, **GEOM, spec_k=2)
        for i in range(3):
            crowded.submit(_prompt(5 + i, seed=60 + i), 4,
                           temperature=1.0, seed=100 + i)
        r2 = crowded.submit(prompt, 6, temperature=1.0, seed=7)
        crowded.run()
        assert r1.tokens == r2.tokens and r1.logprobs == r2.logprobs

    def test_parity_survives_migration(self, model, params, baseline):
        """A replica crash mid-window migrates in-flight requests to a
        chain replica and the final streams still match the
        undisturbed k=0 single engine — speculation composes with the
        fleet's deterministic-replay contract."""
        class _Crashy:
            def __init__(self, engine, crash_at):
                self.engine, self.crash_at, self.n = engine, crash_at, 0

            def step(self):
                self.n += 1
                if self.n == self.crash_at:
                    raise ReplicaCrashError(
                        f"synthetic crash at step {self.n}")
                return self.engine.step()

            def __getattr__(self, name):
                return getattr(self.engine, name)

        crashy = _Crashy(
            ServeEngine(model, params, **GEOM, spec_k=3), crash_at=3)
        other = ServeEngine(model, params, **GEOM, spec_k=3)
        router = Router([crashy, other], probe_backoff_ms=10_000.0)
        hs = [router.submit(_prompt(L, seed=ps), n, temperature=t,
                            seed=i)
              for i, (ps, L, n, t) in enumerate(MIXED)]
        with pytest.warns(UserWarning, match="marked unhealthy"):
            router.run()
        assert all(h.done for h in hs)
        assert [list(h.tokens) for h in hs] == [t for t, _ in baseline]
        assert router.accounting_ok()

    def test_parity_survives_hot_swap(self, model, params):
        """swap_params on a chain engine: version stamps stay
        non-decreasing (one stamp per token, bursts included), and
        post-swap requests match a fresh k=0 engine built on the new
        weights — the subscriber's cutover contract under
        speculation."""
        params2 = model.init(jax.random.key(1))
        eng = ServeEngine(model, params, **GEOM, spec_k=3)
        h1 = eng.submit(_prompt(6, seed=3), 6, temperature=0.8, seed=2)
        while len(h1.tokens) < 2:
            eng.step()
        eng.swap_params(params2, version=2)
        h2 = eng.submit(_prompt(7, seed=4), 5, temperature=0.8, seed=9)
        eng.run()
        assert len(h1.token_versions) == len(h1.tokens)
        assert h1.token_versions == sorted(h1.token_versions)
        assert set(h2.token_versions) == {2}
        ref = ServeEngine(model, params2, **GEOM)
        r2 = ref.submit(_prompt(7, seed=4), 5, temperature=0.8, seed=9)
        ref.run()
        assert h2.tokens == r2.tokens and h2.logprobs == r2.logprobs

    @pytest.mark.slow  # three int8-engine compiles; f32 chain parity
    # is pinned fast above (test_bitwise_parity_vs_k0) and the int8 x
    # chain composition is enforced on every committed spec_sweep cell.
    def test_parity_within_int8_family(self, model, params):
        """decode_quant="int8" changes the sampled stream (quantized
        logits) but chain parity holds WITHIN the family: int8 chain
        == int8 k=0, and the swap re-quantizes (stream still matches
        a fresh int8 engine on the new weights)."""
        q0 = ServeEngine(model, params, **GEOM, decode_quant="int8")
        ref = _submit_mixed(q0)
        q0.run()
        qc = ServeEngine(model, params, **GEOM, decode_quant="int8",
                         spec_k=4)
        hs = _submit_mixed(qc)
        qc.run()
        assert _streams(hs) == _streams(ref)
        params2 = model.init(jax.random.key(1))
        qc.swap_params(params2, version=2)
        h = qc.submit(_prompt(6, seed=8), 5, temperature=0.5, seed=3)
        qc.run()
        fresh = ServeEngine(model, params2, **GEOM, decode_quant="int8")
        r = fresh.submit(_prompt(6, seed=8), 5, temperature=0.5, seed=3)
        fresh.run()
        assert h.tokens == r.tokens and h.logprobs == r.logprobs

    def test_eos_mid_window_stops_exactly(self, model, params):
        """A request hitting EOS inside a chain window emits exactly
        the k=0 prefix — the overrun columns' garbage is discarded at
        harvest, never emitted."""
        ref = ServeEngine(model, params, **GEOM)
        r = ref.submit(_prompt(6, seed=21), 10, seed=5)
        ref.run()
        eos = r.tokens[3]
        a = ServeEngine(model, params, **GEOM)
        ra = a.submit(_prompt(6, seed=21), 10, seed=5, eos_id=eos)
        a.run()
        b = ServeEngine(model, params, **GEOM, spec_k=6)
        rb = b.submit(_prompt(6, seed=21), 10, seed=5, eos_id=eos)
        b.run()
        assert rb.tokens == ra.tokens == r.tokens[:4]
        assert rb.logprobs == ra.logprobs
        assert b.accounting_ok()


# ---------------------------------------------------------------------------
# KV rollback: the fused families' pool invariant
# ---------------------------------------------------------------------------

class TestKVRollback:
    @pytest.mark.parametrize("knobs", [
        dict(spec_k=3, spec_draft="self-1"),
        # self-2 only widens the early-exit depth self-1 already pins.
        pytest.param(dict(spec_k=5, spec_draft="self-2"),
                     marks=pytest.mark.slow),
        dict(spec_k=4, spec_draft="quant", decode_quant="int8"),
    ])
    def test_accounting_holds_after_every_step(self, model, params,
                                               knobs):
        """free + Σallocated == total between ALL steps, not just at
        drain — rejection's trim_blocks rollback can never leak or
        double-free a page."""
        eng = ServeEngine(model, params, **GEOM, **knobs)
        hs = _submit_mixed(eng)
        steps = 0
        while eng.step():
            steps += 1
            assert eng.accounting_ok(), f"pool imbalance at step {steps}"
        assert all(h.done for h in hs)
        assert _ledger_ok(eng, hs)

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_rollback_fuzz(self, model, params, seed):
        """Seeded random workloads at temperature 1.0 — the
        lowest-acceptance regime, maximum rollback churn. After the
        drain: full budgets emitted, ledger identity, pool balanced."""
        rng = np.random.default_rng(seed)
        eng = ServeEngine(model, params, **GEOM, spec_k=3,
                          spec_draft="self-1")
        hs = []
        for i in range(8):
            L = int(rng.integers(4, 14))
            n = int(rng.integers(2, 9))
            hs.append(eng.submit(
                rng.integers(0, 1024, size=L, dtype=np.int64), n,
                temperature=1.0, seed=int(rng.integers(0, 2**31 - 1))))
        eng.run()
        assert all(h.done for h in hs)
        assert all(len(h.tokens) == h.max_new_tokens for h in hs)
        assert eng.accounting_ok()
        assert _ledger_ok(eng, hs)

    def test_no_block_leak_across_many_requests(self, model, params):
        """120 requests through one fused engine: the free list ends
        exactly where it started."""
        eng = ServeEngine(model, params, **GEOM, spec_k=2,
                          spec_draft="self-1")
        free0 = eng.pool.free_count
        for i in range(120):
            eng.submit(_prompt(4 + i % 7, seed=i), 1 + i % 5,
                       temperature=float(i % 2), seed=i)
        eng.run()
        assert eng.pool.free_count == free0
        assert eng.accounting_ok()


# ---------------------------------------------------------------------------
# The int8 quantizer and its kernels
# ---------------------------------------------------------------------------

class TestQuantizer:
    def test_roundtrip_error_bound(self):
        """Symmetric per-output-channel int8: reconstruction error is
        at most half a quantization step per column, s_c / 2."""
        rng = np.random.default_rng(0)
        w = rng.normal(size=(64, 48)).astype(np.float32) \
            * rng.uniform(0.01, 10.0, size=(1, 48)).astype(np.float32)
        qw = quantize_weight(jnp.asarray(w))
        assert qw.q.dtype == jnp.int8
        assert int(jnp.max(jnp.abs(qw.q))) <= 127
        err = np.abs(np.asarray(dequantize(qw)) - w)
        bound = np.asarray(qw.s)[None, :] / 2 + 1e-7
        assert (err <= bound).all()

    def test_zero_column_is_exact_and_finite(self):
        w = jnp.zeros((8, 4), jnp.float32)
        qw = quantize_weight(w)
        out = dequantize(qw)
        assert bool(jnp.all(jnp.isfinite(qw.s)))
        assert bool(jnp.all(out == 0))

    def test_reshape_layouts_match_callsites(self):
        # A (d_ff, d_model) wo quantizes through the same (-1, dm)
        # reshape its matmul call site applies.
        w = jnp.asarray(np.random.default_rng(1).normal(
            size=(4, 16, 32)).astype(np.float32))
        qw = quantize_weight(w, reshape=(-1, 32))
        assert qw.shape == (64, 32)

    def test_non_2d_without_reshape_rejected(self):
        with pytest.raises(ValueError, match="2-D matmul layout"):
            quantize_weight(jnp.zeros((2, 3, 4)))

    def test_qdot_fp_path_is_bitwise_neutral(self):
        """For a plain array qdot traces exactly the pre-quantization
        program — fp engines are bitwise unchanged by the refactor."""
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(3, 5, 32)).astype(np.float32))
        w = jnp.asarray(rng.normal(size=(32, 2, 24)).astype(np.float32))
        got = qdot(x, w, jnp.float32, reshape=(32, 48))
        want = jnp.dot(x, w.astype(jnp.float32).reshape(32, 48),
                       preferred_element_type=jnp.float32)
        np.testing.assert_array_equal(np.asarray(got),
                                      np.asarray(want))

    def test_pallas_kernel_matches_xla_reference(self):
        """The Pallas int8 matmul (interpret mode off-TPU) computes
        the same contraction as qdot's XLA reference path — including
        the non-lane-aligned shapes the wrapper pads."""
        from tpu_ddp.ops.pallas.quant_matmul import int8_matmul
        rng = np.random.default_rng(3)
        for m, k, n in [(1, 64, 48), (5, 130, 200), (8, 128, 128)]:
            x = jnp.asarray(rng.normal(size=(m, k)).astype(np.float32))
            qw = quantize_weight(jnp.asarray(
                rng.normal(size=(k, n)).astype(np.float32)))
            got = int8_matmul(x, qw.q, qw.s, interpret=True)
            want = qdot(x, qw, jnp.float32)
            assert got.shape == (m, n)
            np.testing.assert_allclose(np.asarray(got),
                                       np.asarray(want),
                                       rtol=1e-5, atol=1e-5)

    def test_nll_drift_within_quality_bar(self, model, params):
        """The committed bar: quantized decode within 0.25% of fp32
        mean NLL on a seeded eval stream (spec_sweep enforces the
        same bound on every run)."""
        qparams = quantize_params(model, params)
        rng = np.random.default_rng(3)
        toks = jnp.asarray(
            rng.integers(1, 1024, size=(4, 32)).astype(np.int32))
        d = nll_drift(model, params, qparams, toks)
        assert d["rel_drift"] <= 0.0025
        assert d["greedy_agreement"] >= 0.95
        assert np.isfinite(d["max_abs_logit_err"])

    def test_quantized_tree_is_a_pytree(self, model, params):
        """QuantizedWeight flows through tree ops like a dense leaf
        pair — jit argument passing and donation depend on it."""
        qparams = quantize_params(model, params)
        leaves = jax.tree_util.tree_leaves(qparams)
        assert any(l.dtype == jnp.int8 for l in leaves)
        td1 = jax.tree_util.tree_structure(qparams)
        td2 = jax.tree_util.tree_structure(
            jax.tree_util.tree_map(lambda x: x, qparams))
        assert td1 == td2
        blk = qparams["blocks"][0]
        assert isinstance(blk["wo"], QuantizedWeight)
        assert blk["ln1"] is params["blocks"][0]["ln1"]  # passthrough


# ---------------------------------------------------------------------------
# Knob surfaces
# ---------------------------------------------------------------------------

class TestKnobs:
    def test_grammar(self):
        assert parse_spec_draft("chain") == ("chain", None)
        assert parse_spec_draft("quant") == ("quant", None)
        assert parse_spec_draft("self-2") == ("self", 2)
        for junk in ("self-0", "self-x", "draft", ""):
            with pytest.raises(ValueError, match="spec_draft"):
                parse_spec_draft(junk)
        assert all(parse_spec_draft(s) for s in SPEC_DRAFTS)

    def test_env_defaults_flow_into_engine(self, model, params,
                                           monkeypatch):
        monkeypatch.setenv("TPU_DDP_SPEC_K", "3")
        monkeypatch.setenv("TPU_DDP_SPEC_DRAFT", "self-1")
        monkeypatch.setenv("TPU_DDP_DECODE_QUANT", "int8")
        eng = ServeEngine(model, params, **GEOM)
        assert eng.spec_k == 3
        assert eng.spec_draft == "self-1"
        assert eng.decode_quant == "int8"

    @pytest.mark.parametrize("env,junk,match", [
        ("TPU_DDP_SPEC_K", "-1", "TPU_DDP_SPEC_K"),
        ("TPU_DDP_SPEC_DRAFT", "oracle", "TPU_DDP_SPEC_DRAFT"),
        ("TPU_DDP_DECODE_QUANT", "int3", "TPU_DDP_DECODE_QUANT"),
    ])
    def test_junk_env_rejected(self, env, junk, match, monkeypatch):
        from tpu_ddp.utils.config import TrainConfig
        monkeypatch.setenv(env, junk)
        with pytest.raises(ValueError, match=match):
            TrainConfig()

    def test_coupled_violation_draft_deeper_than_model(self, model,
                                                       params):
        # TransformerLM-tiny has 2 layers: a self-5 draft cannot
        # early-exit past the model's own depth.
        with pytest.raises(ValueError, match="draft depth"):
            ServeEngine(model, params, **GEOM, spec_k=2,
                        spec_draft="self-5")

    def test_negative_spec_k_rejected(self, model, params):
        with pytest.raises(ValueError, match="spec_k"):
            ServeEngine(model, params, **GEOM, spec_k=-1)

    def test_bad_decode_quant_rejected(self, model, params):
        with pytest.raises(ValueError, match="decode_quant"):
            ServeEngine(model, params, **GEOM, decode_quant="int4")

    def test_lower_spec_step_gates(self, model, params):
        """The audit surface exists exactly when a fused program does:
        chain and k=0 engines have no spec program to lower."""
        eng = ServeEngine(model, params, **GEOM, spec_k=2)
        with pytest.raises(ValueError, match="chain"):
            eng.lower_spec_step()
        fused = ServeEngine(model, params, **GEOM, spec_k=2,
                            spec_draft="self-1")
        assert fused.lower_spec_step() is not None

    def test_tune_space_carries_spec_knobs(self):
        from tpu_ddp.tune.space import KNOBS, Workload, violations
        names = {k.name for k in KNOBS}
        assert {"spec_k", "spec_draft", "decode_quant"} <= names
        ctx = Workload()
        # Coupled-knob pruning: an inert draft family and a
        # disagg-fleet speculation cell are both rejected.
        assert violations({"spec_draft": "self-1", "spec_k": 0}, ctx)
        assert violations({"spec_k": 4, "fleet_roles": "disagg"}, ctx)
        assert violations({"spec_draft": "self-1", "spec_k": 4},
                          ctx) == []


# ---------------------------------------------------------------------------
# The TPOT bugfix: percentiles from emission stamps, not uniform math
# ---------------------------------------------------------------------------

class _BurstEngine:
    """Forced-accept stub: completes every request in one step,
    stamping token_times as a BURST — (n-1) near-zero gaps then one
    long inter-burst gap. The old uniform (finished-first)/(n-1)
    estimate reports every gap as the mean and hides the burst; the
    stamped computation must expose both tails."""

    def __init__(self, gap_s=0.1, stamp=True):
        self.gap_s = gap_s
        self.stamp = stamp
        self._pending: list[Request] = []
        self._rid = 0

    def submit(self, prompt, max_new, temperature=0.0, seed=0,
               tenant="default"):
        req = Request(rid=self._rid, prompt=np.asarray(prompt, np.int32),
                      max_new_tokens=int(max_new),
                      submitted_at=time.perf_counter())
        self._rid += 1
        self._pending.append(req)
        return req

    def step(self):
        if not self._pending:
            return False
        for req in self._pending:
            now = time.perf_counter()
            n = req.max_new_tokens
            # n-1 gaps of 1us (the intra-burst emissions) + one
            # inter-burst gap: bursty by construction.
            stamps = [now + 1e-6 * i for i in range(n - 1)]
            stamps.append(stamps[-1] + self.gap_s)
            req.tokens = list(range(n))
            req.logprobs = [0.0] * n
            req.token_versions = [0] * n
            req.token_times = stamps if self.stamp else []
            req.first_token_at = stamps[0]
            req.finished_at = stamps[-1]
            req.done = True
        self._pending = []
        return True


class TestTPOTFromStamps:
    def test_bursty_stamps_drive_percentiles(self):
        """With 7 near-zero gaps and one 100ms gap per request, the
        stamped p50 is ~0 and the p99 ~100ms; the old uniform
        estimate would have put BOTH at ~12.6ms. This is the loadgen
        regression the speculative burst exposed."""
        eng = _BurstEngine(gap_s=0.1)
        specs = [RequestSpec(prompt=(1, 2, 3), max_new_tokens=9,
                             temperature=0.0, seed=i)
                 for i in range(6)]
        out = run_load(eng, specs, rate=1000.0, seed=0)
        assert out["n_completed"] == 6
        assert out["tpot_p50_ms"] < 1.0          # intra-burst gap
        assert out["tpot_p99_ms"] > 50.0         # inter-burst gap
        # The uniform estimate both gaps would have collapsed to:
        uniform_ms = 0.1 / 8 * 1e3
        assert abs(out["tpot_p50_ms"] - uniform_ms) > 5.0
        assert abs(out["tpot_p99_ms"] - uniform_ms) > 5.0

    def test_stampless_handles_fall_back_to_uniform(self):
        """A handle built outside the engine (no stamps) still weighs
        in via synthetic uniform gaps instead of being dropped: with
        a 0.08s first-to-finish span over 4 gaps, every synthetic gap
        is exactly 20ms."""
        eng = _BurstEngine(gap_s=0.08, stamp=False)
        specs = [RequestSpec(prompt=(1, 2), max_new_tokens=5,
                             temperature=0.0, seed=0)]
        out = run_load(eng, specs, rate=1000.0, seed=0)
        # span = 3 * 1us + 0.08s over n-1 = 4 uniform gaps ≈ 20ms each
        assert out["tpot_p50_ms"] == pytest.approx(20.0, abs=1.0)
        assert out["tpot_p99_ms"] == pytest.approx(20.0, abs=1.0)

    def test_real_chain_engine_stamps_every_token(self, model, params):
        """End to end on the real engine: one stamp per token, stamps
        non-decreasing, and run_load's TPOT fields populate."""
        eng = ServeEngine(model, params, **GEOM, spec_k=3)
        specs = [RequestSpec(prompt=tuple(_prompt(5 + i, seed=i)),
                             max_new_tokens=4 + i, temperature=0.5,
                             seed=i)
                 for i in range(4)]
        out = run_load(eng, specs, rate=1000.0, seed=1)
        assert out["n_completed"] == 4
        assert out["tpot_p50_ms"] is not None
        assert out["tpot_p99_ms"] >= out["tpot_p50_ms"]
