"""Dropout for the LM family (TransformerLM.dropout_rate).

Decisive properties: rng-gated (no rng -> deterministic eval, exactly
the dropout-free graph), per-step/per-shard key discipline in the
trainer, preserved loss semantics (model still trains), and pipeline-
geometry-invariant masks under pp (keys derive from microbatch + GLOBAL
layer index, so pp=1 and pp=2 draw identical masks).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.train.lm import LMTrainer, PipelineLMTrainer, make_lm_batch


def _model(rate=0.5, **kw):
    kw.setdefault("max_seq_len", 16)
    return make_transformer("TransformerLM-tiny", dropout_rate=rate,
                            compute_dtype=jnp.float32, **kw)


def _tokens(b=2, L=16):
    return jax.random.randint(jax.random.key(0), (b, L), 0, 1024)


class TestModelDropout:
    def test_no_rng_is_exactly_dropout_free(self):
        """apply without rng == the rate-0 model's apply, bit for bit —
        eval and generation never see dropout."""
        drop = _model(0.5)
        base = _model(0.0)
        params = drop.init(jax.random.key(1))
        t = _tokens()
        np.testing.assert_array_equal(
            np.asarray(drop.apply(params, t)),
            np.asarray(base.apply(params, t)))

    def test_rng_activates_and_is_deterministic(self):
        model = _model(0.5)
        params = model.init(jax.random.key(1))
        t = _tokens()
        clean = np.asarray(model.apply(params, t))
        r = jax.random.key(7)
        a = np.asarray(model.apply(params, t, rng=r))
        b = np.asarray(model.apply(params, t, rng=r))
        c = np.asarray(model.apply(params, t, rng=jax.random.key(8)))
        np.testing.assert_array_equal(a, b)       # same key -> same mask
        assert np.abs(a - clean).max() > 1e-3     # dropout did something
        assert np.abs(a - c).max() > 1e-3         # new key -> new mask

    def test_rate_zero_ignores_rng(self):
        model = _model(0.0)
        params = model.init(jax.random.key(1))
        t = _tokens()
        np.testing.assert_array_equal(
            np.asarray(model.apply(params, t, rng=jax.random.key(3))),
            np.asarray(model.apply(params, t)))

    @pytest.mark.slow  # remat+dropout double compile; logic also covered by test_vit remat
    def test_remat_matches_dense_under_dropout(self):
        """jax.checkpoint must replay the SAME masks in the backward."""
        dense = _model(0.3)
        remat = _model(0.3, remat_blocks=True)
        params = dense.init(jax.random.key(2))
        t = _tokens()
        r = jax.random.key(9)

        def loss(model, p):
            return jnp.mean(model.apply(p, t, rng=r) ** 2)

        g_d = jax.grad(lambda p: loss(dense, p))(params)
        g_r = jax.grad(lambda p: loss(remat, p))(params)
        for a, b in zip(jax.tree.leaves(g_d), jax.tree.leaves(g_r)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


class TestTrainerDropout:
    def test_steps_use_fresh_masks_and_resume_replays_them(self, devices,
                                                           tmp_path):
        """Two runs from the same checkpoint take identical steps (the
        key derives from the state's step), and successive steps use
        different masks (loss path changes even on a fixed batch)."""
        model = _model(0.4, max_seq_len=32)
        mesh = make_mesh(devices[:2], dp=2)
        tr = LMTrainer(model, mesh)
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(0).integers(0, 1024, size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, closs = tr.train_step(state, x, y)
        restored = tr.restore_checkpoint(str(tmp_path))
        resumed, rloss = tr.train_step(restored, x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)
        # Mask freshness: the key derives from state.step. Restore the
        # same checkpoint again (train_step donated the first restore's
        # buffers) but advance step before stepping — identical params,
        # batch, and loss math, so any loss change can only come from a
        # different dropout mask.
        again = tr.restore_checkpoint(str(tmp_path))
        bumped = type(again)(params=again.params,
                             opt_state=again.opt_state,
                             step=again.step + 1)
        _, bloss = tr.train_step(bumped, x, y)
        assert abs(float(np.mean(np.asarray(rloss)))
                   - float(np.mean(np.asarray(bloss)))) > 1e-6

    def test_trains_with_dropout(self, devices):
        model = _model(0.1, max_seq_len=32)
        tr = LMTrainer(model, make_mesh(devices[:2], dp=2))
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(1).integers(0, 1024, size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(6):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_grad_accum_composes(self, devices):
        model = _model(0.2, max_seq_len=32)
        tr = LMTrainer(model, make_mesh(devices[:2], dp=2), grad_accum=2)
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(2).integers(0, 1024, size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, loss = tr.train_step(state, x, y)
        assert np.isfinite(np.asarray(loss)).all()

    def test_tp_shards_share_masks(self, devices):
        """The key-discipline invariant, tested DIRECTLY on the folded
        keys: mp shards must receive the SAME dropout key (the residual
        stream is replicated over tp — different masks would desync the
        psum'd activations) while dp shards must receive DIFFERENT keys
        (they hold different tokens)."""
        from jax.sharding import PartitionSpec as P
        model = _model(0.3, max_seq_len=32)
        mesh = make_mesh(devices[:4], dp=2, mp=2)
        tr = LMTrainer(model, mesh)

        def fn(key):
            k = tr._decorrelate_rng(key)
            return jax.random.key_data(k).reshape(1, 1, -1)

        out = np.asarray(jax.jit(jax.shard_map(
            fn, mesh=mesh, in_specs=P(),
            out_specs=P("dp", "mp", None), check_vma=False))(
                jax.random.key(0)))
        assert out.shape[:2] == (2, 2)
        assert (out[:, 0] == out[:, 1]).all()   # identical across mp
        assert (out[0] != out[1]).any()         # distinct across dp

        # And the step itself runs coherently under dp=1 x tp=2.
        tr2 = LMTrainer(model, make_mesh(devices[:2], dp=1, mp=2))
        state = tr2.init_state(seed=0)
        tokens = np.random.default_rng(3).integers(0, 1024, size=(2, 33))
        x, y = tr2.put_batch(*make_lm_batch(tokens))
        state, loss = tr2.train_step(state, x, y)
        assert np.isfinite(np.ravel(np.asarray(loss))).all()

    @pytest.mark.slow  # three pp-trainer compiles; the pp mask keying is
    # pinned fast by test_pipeline_dropout_key_varies_by_step
    def test_pipeline_dropout_geometry_invariant(self, devices):
        """Dropout under pp: masks key on (microbatch, GLOBAL layer), so
        the same seed gives IDENTICAL gradients at pp=1 and pp=2 — the
        stage split cannot change which mask a layer sees."""
        from tpu_ddp.ops.optim import SGD

        tokens = np.random.default_rng(4).integers(0, 1024, size=(4, 33))
        params = {}
        for pp in (1, 2):
            model = _model(0.3, num_layers=2, max_seq_len=32)
            mesh = make_mesh(devices[:pp], dp=1, pp=pp)
            tr = PipelineLMTrainer(
                model, mesh, num_micro=2, dropout_seed=5,
                optimizer=SGD(learning_rate=0.1, momentum=0.9,
                              weight_decay=1e-4))
            state = tr.init_state(seed=7)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            state, loss = tr.train_step(state, x, y)
            assert np.isfinite(np.ravel(np.asarray(loss))).all()
            params[pp] = jax.device_get(state.params)
        for a, b in zip(jax.tree.leaves(params[1]),
                        jax.tree.leaves(params[2])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-5, atol=3e-6)

    def test_pipeline_dropout_key_varies_by_step(self, devices):
        """Two steps from the same state must draw different masks (the
        key folds the step count): stepping twice from identical states
        with the SAME batch produces different second-step params than
        replaying step 1's key would."""
        model = _model(0.5, num_layers=2, max_seq_len=32)
        mesh = make_mesh(devices[:2], dp=1, pp=2)
        tr = PipelineLMTrainer(model, mesh, num_micro=2, dropout_seed=1)
        tokens = np.random.default_rng(8).integers(0, 1024, size=(2, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        s1, l1 = tr.train_step(tr.init_state(seed=0), x, y)
        # Re-run step at the SAME step counter (fresh identical state —
        # the first call donated its buffers): identical loss (resume-
        # exact determinism)...
        s1b, l1b = tr.train_step(tr.init_state(seed=0), x, y)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l1b))
        # ...but the next step (different counter) sees fresh masks: its
        # loss differs from re-evaluating with step 1's state/key pair.
        s2, l2 = tr.train_step(s1, x, y)
        assert not np.allclose(np.asarray(l2), np.asarray(l1))
