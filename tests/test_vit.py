"""Vision Transformer family (tpu_ddp/models/vit.py).

Decisive properties: the functional contract matches the rest of the
zoo (init/apply, Trainer-compatible), patchify is a faithful spatial
decomposition, flash/remat options change nothing numerically, and the
model trains through the DP engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models import get_model
from tpu_ddp.models.vit import ViTModel, make_vit
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig


def _model(**kw):
    kw.setdefault("compute_dtype", jnp.float32)
    return make_vit("ViT-tiny", num_layers=2, d_model=64, d_ff=128,
                    num_heads=2, **kw)


class TestModel:
    def test_registry_and_shapes(self):
        model = get_model("ViT-tiny", num_layers=2, d_model=64, d_ff=128,
                          num_heads=2, compute_dtype=jnp.float32)
        params = model.init(jax.random.key(0))
        x = jnp.zeros((2, 32, 32, 3), jnp.float32)
        logits = model.apply(params, x)
        assert logits.shape == (2, 10)
        assert logits.dtype == jnp.float32
        assert model.num_patches == 64

    def test_validation(self):
        with pytest.raises(ValueError, match="patch_size"):
            ViTModel(image_size=32, patch_size=5)
        with pytest.raises(ValueError, match="num_heads"):
            ViTModel(d_model=100, num_heads=3)
        model = _model()
        params = model.init(jax.random.key(0))
        with pytest.raises(ValueError, match="expected 32x32"):
            model.apply(params, jnp.zeros((1, 16, 16, 3)))

    def test_patchify_is_spatial_decomposition(self):
        """Patch row k must contain exactly the pixels of spatial patch
        (k // g, k % g) in raster order."""
        model = _model()
        x = jnp.arange(32 * 32 * 3, dtype=jnp.float32).reshape(
            1, 32, 32, 3)
        tok = model._patchify(x)
        g, p = 8, 4
        for k in (0, 9, 63):
            ph, pw = divmod(k, g)
            want = x[0, ph * p:(ph + 1) * p, pw * p:(pw + 1) * p, :]
            np.testing.assert_array_equal(
                np.asarray(tok[0, k]), np.asarray(want).reshape(-1))

    def test_position_embedding_breaks_permutation_invariance(self):
        """Without pos embeddings GAP attention would be permutation-
        invariant over patches; with them, swapping two distinct patches
        must change the logits."""
        model = _model()
        params = model.init(jax.random.key(1))
        x = jax.random.normal(jax.random.key(2), (1, 32, 32, 3))
        x2 = x.at[:, :4, :4].set(x[:, :4, 4:8]).at[:, :4, 4:8].set(
            x[:, :4, :4])
        a = np.asarray(model.apply(params, x))
        b = np.asarray(model.apply(params, x2))
        assert np.abs(a - b).max() > 1e-4

    def test_flash_and_remat_match_dense(self):
        base = _model()
        params = base.init(jax.random.key(3))
        x = jax.random.normal(jax.random.key(4), (2, 32, 32, 3))
        want = base.apply(params, x)
        got_flash = _model(use_flash=True).apply(params, x)
        np.testing.assert_allclose(np.asarray(got_flash),
                                   np.asarray(want),
                                   rtol=2e-4, atol=2e-4)
        got_remat = _model(remat_blocks=True).apply(params, x)
        np.testing.assert_array_equal(np.asarray(got_remat),
                                      np.asarray(want))


class TestTraining:
    def test_trains_under_fused_dp(self, devices):
        cfg = TrainConfig.preset("vit_cifar10", global_batch_size=16,
                                 learning_rate=0.01)
        model = _model()
        mesh = make_mesh(devices[:4])
        tr = Trainer(model, cfg, strategy="fused", mesh=mesh)
        state = tr.init_state()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 10, size=16).astype(np.int32)
        xb, yb, wb = tr.put_batch(x, y)
        losses = []
        for _ in range(4):
            state, loss = tr.train_step(state, xb, yb, wb)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_eval_runs(self, devices):
        cfg = TrainConfig.preset("vit_cifar10", global_batch_size=8)
        model = _model()
        tr = Trainer(model, cfg, strategy="none")
        state = tr.init_state()
        rng = np.random.default_rng(1)
        batches = [(rng.normal(size=(8, 32, 32, 3)).astype(np.float32),
                    rng.integers(0, 10, size=8).astype(np.int32))]
        out = tr.evaluate(state, batches, log=lambda s: None)
        assert 0.0 <= out["test_accuracy"] <= 1.0
        assert np.isfinite(out["test_loss"])
