"""scripts/comm_volume.py — HLO collective extraction.

The communication-volume ladder (EXPERIMENTS.md) hangs off this parser,
so its op/shape/byte accounting is pinned here against hand-written HLO
snippets; the full compile-and-extract path runs in the script itself
(and is exercised by the committed experiments/comm_volume.json).
"""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "comm_volume", os.path.join(REPO, "scripts", "comm_volume.py"))
comm_volume = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(comm_volume)


HLO = """
HloModule jit_step
ENTRY main {
  %p0 = f32[1024,512]{1,0} parameter(0)
  %ar = f32[1024,512]{1,0} all-reduce(f32[1024,512]{1,0} %p0), replica_groups={}
  %rs = f32[128,512]{1,0} reduce-scatter(f32[1024,512]{1,0} %ar), dimensions={0}
  %ag = bf16[1024,512]{1,0} all-gather(bf16[128,512]{1,0} %x), dimensions={0}
  %cp = f32[64]{0} collective-permute(f32[64]{0} %y), source_target_pairs={{0,1}}
  %a2a = (f32[32]{0}, f32[32]{0}) all-to-all(f32[32]{0} %a, f32[32]{0} %b)
  %add = f32[64]{0} add(f32[64]{0} %cp, f32[64]{0} %cp)
}
"""


class TestShapeBytes:
    def test_simple(self):
        assert comm_volume._shape_bytes("f32[1024,512]{1,0}") == \
            1024 * 512 * 4

    def test_bf16(self):
        assert comm_volume._shape_bytes("bf16[128,512]{1,0}") == \
            128 * 512 * 2

    def test_tuple(self):
        assert comm_volume._shape_bytes("(f32[32]{0}, f32[32]{0})") == \
            2 * 32 * 4

    def test_scalar_dims(self):
        assert comm_volume._shape_bytes("f32[]") == 4


class TestCollectiveVolume:
    def test_counts_and_payloads(self):
        v = comm_volume.collective_volume(HLO, n_devices=8)
        ops = v["ops"]
        assert ops["all-reduce"]["count"] == 1
        assert ops["all-reduce"]["payload_bytes"] == 1024 * 512 * 4
        assert ops["reduce-scatter"]["count"] == 1
        assert ops["reduce-scatter"]["payload_bytes"] == 128 * 512 * 4
        assert ops["all-gather"]["count"] == 1
        assert ops["all-gather"]["payload_bytes"] == 1024 * 512 * 2
        assert ops["collective-permute"]["count"] == 1
        assert ops["all-to-all"]["count"] == 1
        # Non-collective instructions (add) never counted.
        assert v["total_collectives"] == 5

    def test_ring_wire_model(self):
        v = comm_volume.collective_volume(HLO, n_devices=8)
        ops = v["ops"]
        frac = 7 / 8
        ar = 1024 * 512 * 4
        assert ops["all-reduce"]["wire_bytes_per_device"] == 2 * frac * ar
        # reduce-scatter result is the 1/N shard; wire = frac * input.
        assert ops["reduce-scatter"]["wire_bytes_per_device"] == \
            frac * 128 * 512 * 4 * 8
        assert ops["all-gather"]["wire_bytes_per_device"] == \
            frac * 1024 * 512 * 2
        assert ops["collective-permute"]["wire_bytes_per_device"] == 64 * 4

    def test_zero_identity_holds_on_real_artifact(self):
        """The committed ladder must show the all_reduce ==
        reduce_scatter + all_gather byte identity (part4/5 vs part3) and
        gather/scatter's multiple: the claims EXPERIMENTS.md §comm makes."""
        import json
        path = os.path.join(REPO, "experiments", "comm_volume.json")
        if not os.path.exists(path):
            import pytest
            pytest.skip("experiments/comm_volume.json not generated yet")
        d = json.load(open(path))
        rungs = d["rungs"]
        w3 = rungs["part3"]["total_wire_bytes_per_device"]
        for p in ("part4", "part5"):
            wz = rungs[p]["total_wire_bytes_per_device"]
            assert abs(wz - w3) / w3 < 0.02, (p, wz, w3)
        assert rungs["part2a"]["total_wire_bytes_per_device"] > 2 * w3
