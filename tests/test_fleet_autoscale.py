"""Autoscaling multi-tenant fleet (tpu_ddp/fleet/autoscale.py,
tpu_ddp/serve/scheduler.py tenancy, docs/DESIGN.md §25): the replica
lifecycle control plane plus SLO classes.

The bars are the ones the fleet was built on, now under elasticity:

- **Bitwise parity across lifecycle.** A scale-down drain migrates
  every unfinished stream via ``continuation_of`` — tokens identical
  to the undisturbed run, zero dropped, zero shed.
- **Per-tenant identity.** ``completed + cancelled + shed ==
  submitted`` holds PER TENANT through mixed cancel/shed/drain storms,
  and a cancel storm leaves no ghost load in the autoscaler's backlog
  signal (the regression this PR's Router.cancel fix pins).
- **Namespace isolation.** Bitwise-identical prompts under different
  tenants share NOTHING: zero cross-namespace cached tokens, identical
  output streams.
- **Zero new jit surfaces.** Booting a replica reuses the memoized
  step builders (no compile-cache growth) and the committed
  graph-audit artifact stays at 19 programs.
"""

import json
import os
import pathlib

import jax
import jax.numpy as jnp
import pytest

from tpu_ddp.fleet import Autoscaler, Router
from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.serve import (
    ServeEngine,
    TenantClass,
    make_shared_prefix_workload,
    make_trace,
    parse_tenant_classes,
    run_trace,
)

GEOM = dict(num_slots=4, block_size=8, prefill_chunk=8)

MIXED = [(0, 5, 6, 0.0), (1, 9, 5, 0.0), (2, 12, 4, 0.7),
         (3, 8, 6, 1.0)]


@pytest.fixture(scope="module")
def model():
    return make_transformer("TransformerLM-tiny", max_seq_len=64,
                            compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def _prompt(n, seed=0, vocab=1024):
    return jax.random.randint(jax.random.key(seed), (n,), 0,
                              vocab).tolist()


def _submit_mixed(target, tenants=("gold", "silver", "bronze",
                                   "gold")):
    return [target.submit(_prompt(L, seed=ps), n, temperature=t,
                          seed=i, tenant=tenants[i])
            for i, (ps, L, n, t) in enumerate(MIXED)]


# ---------------------------------------------------------------------------
# Tenant classes: parsing + config/env surfaces
# ---------------------------------------------------------------------------

def test_tenant_class_parsing():
    classes = parse_tenant_classes(
        "gold=3:250:4096,silver=2:500,bronze=1")
    assert classes["gold"] == TenantClass("gold", 3, 250.0, 4096)
    assert classes["silver"].weight == 2
    assert classes["silver"].deadline_ms == 500.0
    assert classes["bronze"].token_budget == 0
    assert parse_tenant_classes("") == {}
    assert parse_tenant_classes(None) == {}


@pytest.mark.parametrize("bad", [
    "gold",                       # no '='
    "gold=",                      # no weight
    "gold=fast",                  # non-numeric weight
    "gold=3:a",                   # non-numeric deadline
    "gold=3:250:4096:9",          # too many fields
    "gold=3,gold=2",              # duplicate class
])
def test_tenant_class_parsing_rejects_junk(bad):
    with pytest.raises(ValueError):
        parse_tenant_classes(bad)


def test_config_env_knobs(monkeypatch):
    from tpu_ddp.utils.config import TrainConfig

    monkeypatch.setenv("TPU_DDP_FLEET_AUTOSCALE", "1")
    monkeypatch.setenv("TPU_DDP_SCALE_COOLDOWN_MS", "250")
    monkeypatch.setenv("TPU_DDP_TENANT_CLASSES",
                       "gold=3,bronze=1")
    cfg = TrainConfig()
    assert cfg.fleet_autoscale is True
    assert cfg.scale_cooldown_ms == 250.0
    assert cfg.tenant_classes == "gold=3,bronze=1"


@pytest.mark.parametrize("env,val", [
    ("TPU_DDP_FLEET_AUTOSCALE", "knob-audit-junk"),
    ("TPU_DDP_SCALE_COOLDOWN_MS", "knob-audit-junk"),
    ("TPU_DDP_SCALE_COOLDOWN_MS", "0"),
    ("TPU_DDP_SCALE_COOLDOWN_MS", "-5"),
    ("TPU_DDP_TENANT_CLASSES", "knob-audit-junk"),
])
def test_config_env_rejects_junk(monkeypatch, env, val):
    from tpu_ddp.utils.config import TrainConfig

    monkeypatch.setenv(env, val)
    with pytest.raises(ValueError, match=env):
        TrainConfig()


# ---------------------------------------------------------------------------
# Chaos grammar: the two load-surge kinds
# ---------------------------------------------------------------------------

def test_chaos_parse_load_kinds():
    from tpu_ddp.resilience.chaos import parse_faults

    fc, ts = parse_faults("flash-crowd@3,tenant-storm@5:tenant=bronze")
    assert fc.kind == "flash-crowd" and fc.step == 3 \
        and fc.tenant is None
    assert ts.kind == "tenant-storm" and ts.step == 5 \
        and ts.tenant == "bronze"


def test_chaos_tenant_rules():
    from tpu_ddp.resilience.chaos import parse_faults

    with pytest.raises(ValueError):
        parse_faults("tenant-storm@5")          # storm needs a tenant
    with pytest.raises(ValueError):
        parse_faults("flash-crowd@3:tenant=a")  # crowd is tenant-less
    with pytest.raises(ValueError):
        parse_faults("replica-crash@3:tenant=a")


# ---------------------------------------------------------------------------
# WFQ + class-aware shedding
# ---------------------------------------------------------------------------

def test_wfq_serves_heavier_class_first(model, params):
    """With every slot contended, stride scheduling admits gold 3x as
    often as bronze — gold finishes strictly earlier on average."""
    eng = ServeEngine(model, params,
                      tenant_classes="gold=3,bronze=1", **GEOM)
    hs = {}
    for t in ("gold", "bronze"):
        hs[t] = [eng.submit(_prompt(5, seed=k), 6, tenant=t)
                 for k in range(8)]
    order = []
    while eng.step():
        for t, lst in hs.items():
            for h in lst:
                if h.done and (t, id(h)) not in order:
                    order.append((t, id(h)))
    rank = {key: i for i, key in enumerate(order)}
    mean_gold = sum(rank[("gold", id(h))]
                    for h in hs["gold"]) / len(hs["gold"])
    mean_bronze = sum(rank[("bronze", id(h))]
                      for h in hs["bronze"]) / len(hs["bronze"])
    assert mean_gold < mean_bronze
    assert eng.tenant_accounting_ok() and eng.accounting_ok()


def test_shed_hits_lowest_class_first(model, params):
    """A full admission queue evicts bronze to admit gold — never the
    other way around."""
    eng = ServeEngine(model, params, queue_limit=6,
                      tenant_classes="gold=4,bronze=1", **GEOM)
    bronze = [eng.submit(_prompt(5, seed=k), 4, tenant="bronze")
              for k in range(16)]
    gold = [eng.submit(_prompt(5, seed=100 + k), 4, tenant="gold")
            for k in range(4)]
    eng.run()
    stats = eng.tenant_stats()
    assert stats["gold"]["shed"] == 0
    assert stats["bronze"]["shed"] >= 1
    assert sum(h.shed for h in bronze) == stats["bronze"]["shed"]
    assert all(not h.shed for h in gold)
    assert eng.tenant_accounting_ok() and eng.accounting_ok()


# ---------------------------------------------------------------------------
# Ghost-load regression: cancel storms and the backlog signal
# ---------------------------------------------------------------------------

def test_cancel_storm_leaves_no_ghost_load(model, params):
    """The scale-up signal is outstanding-per-replica: a tenant that
    cancels its whole burst must vanish from the backlog, or the
    autoscaler boots replicas for load that no longer exists."""
    router = Router([ServeEngine(model, params, **GEOM)
                     for _ in range(2)])
    auto = Autoscaler(router, lambda: ServeEngine(model, params,
                                                  **GEOM),
                      min_replicas=1, max_replicas=3,
                      up_tokens_per_replica=8.0,
                      down_tokens_per_replica=2.0,
                      cooldown_ms=1e9, enabled=True)
    keep = [auto.submit(_prompt(5, seed=k), 4, tenant="steady")
            for k in range(2)]
    storm = [auto.submit(_prompt(5, seed=50 + k), 4, tenant="storm")
             for k in range(12)]
    before = auto.outstanding_by_tenant()   # token-weighted backlog
    assert before.get("storm", 0) > 5 * before.get("steady", 1)
    for h in storm:
        assert auto.cancel(h)
    by = auto.outstanding_by_tenant()
    assert by.get("storm", 0) == 0          # the regression pin
    # The scale-up signal sees ONLY the surviving tenant's tokens.
    assert auto.router.outstanding() == by.get("steady", 0)
    assert auto.load_per_replica() <= before["steady"]
    auto.run()
    assert all(h.done and not h.cancelled for h in keep)
    assert auto.outstanding() == 0
    assert auto.tenant_accounting_ok() and auto.accounting_ok()


# ---------------------------------------------------------------------------
# Identity + parity across the scale-down drain
# ---------------------------------------------------------------------------

def test_identity_and_parity_across_drain(model, params):
    """Mixed cancel + shed + scale-down drain: per-tenant identity
    holds everywhere and migrated streams stay bitwise identical."""
    def factory():
        return ServeEngine(model, params, **GEOM)

    eng = factory()
    base = _submit_mixed(eng)
    eng.run()
    baseline = [list(h.tokens) for h in base]

    router = Router([factory(), factory()])
    auto = Autoscaler(router, factory, min_replicas=1, max_replicas=2,
                      enabled=False)
    hs = _submit_mixed(auto)
    extra = auto.submit(_prompt(6, seed=9), 5, tenant="bronze")
    for _ in range(3):
        auto.step()          # partway into decode on both replicas
    assert auto.cancel(extra)
    retired = auto.scale_down()
    assert retired is not None
    assert len(router.replicas) == 1
    auto.run()
    assert [list(h.tokens) for h in hs] == baseline
    assert not any(h.shed or h.cancelled for h in hs)
    assert extra.cancelled and not extra.shed
    assert auto.scale_downs == 1
    assert auto.migrated_on_drain >= 1   # drain caught live streams
    assert auto.tenant_accounting_ok() and auto.accounting_ok()
    by = auto.outstanding_by_tenant()
    assert all(v == 0 for v in by.values())


# ---------------------------------------------------------------------------
# Namespace isolation
# ---------------------------------------------------------------------------

def test_tenant_prefix_namespace_isolation(model, params):
    """Bitwise-identical prompts under different tenants: zero
    cross-namespace cached tokens, bitwise-identical outputs."""
    eng = ServeEngine(model, params, prefix_cache=True,
                      tenant_classes="a=1,b=1", **GEOM)
    specs = make_shared_prefix_workload(4, vocab_size=1024, seed=4,
                                        prefix_len=16)

    def wave(tenant):
        hs = [eng.submit(sp.prompt, sp.max_new_tokens,
                         temperature=sp.temperature, seed=sp.seed,
                         tenant=tenant) for sp in specs]
        eng.run()
        return hs

    a1 = wave("a")
    assert eng.prefix_cached_len(specs[0].prompt, tenant="a") > 0
    assert eng.prefix_cached_len(specs[0].prompt, tenant="b") == 0
    b1 = wave("b")
    assert [list(h.tokens) for h in a1] == [list(h.tokens)
                                            for h in b1]
    assert eng.tenant_accounting_ok() and eng.accounting_ok()


# ---------------------------------------------------------------------------
# Scale-up: boot-from-push, current version, zero new compiles
# ---------------------------------------------------------------------------

def test_scale_up_boots_current_version_no_new_compiles(model, params):
    from tpu_ddp.publish.publisher import Publisher
    from tpu_ddp.publish.subscriber import attach
    from tpu_ddp.serve.engine import (
        _build_decode_step,
        _build_prefill_step,
    )

    def factory():
        return ServeEngine(model, params, **GEOM)

    pub = Publisher(publish_every=1, wire="none", bucket_mb=0.25)
    seed_eng = factory()
    seed_eng.subscriber = attach(pub, seed_eng, name="seed")[0]
    current = jax.tree.map(lambda x: x + 0.01, params)
    pub.publish(params=current, step=1)
    while seed_eng.subscriber.lag:
        seed_eng.step()

    router = Router([seed_eng])
    auto = Autoscaler(router, factory, publisher=pub,
                      min_replicas=1, max_replicas=2, enabled=False)
    d0 = _build_decode_step.cache_info().currsize
    p0 = _build_prefill_step.cache_info().currsize
    booted = auto.scale_up()
    assert booted is not None
    # Same geometry -> the memoized step builders are reused: booting
    # a replica compiles NOTHING new (the graph-audit pin).
    assert _build_decode_step.cache_info().currsize == d0
    assert _build_prefill_step.cache_info().currsize == p0
    assert booted.param_version == pub.version == 1
    assert auto.scale_ups == 1 and len(auto.boot_s) == 1
    assert pub.bootstraps == 1
    # The booted replica serves the CURRENT fleet weights bitwise.
    h0 = seed_eng.submit(_prompt(6, seed=3), 5)
    seed_eng.run()
    h1 = booted.submit(_prompt(6, seed=3), 5)
    booted.run()
    assert list(h0.tokens) == list(h1.tokens)


def test_graph_audit_n_programs_pinned():
    """MoE added exactly THREE jit surfaces (the dp x ep train step —
    the one program with the paired expert all_to_alls — and the
    cached-MoE decode/prefill twins; the sparse publish wire adds none,
    EdgeCodec is host-side): 28 -> 31 programs. Long-context's five
    (tiered-decode/prefill, demote/promote, cp-prefill-ring) before
    that: 23 -> 28. DiLoCo adds exactly ONE (the guarded outer Nesterov
    step; the wire reuses the publish codecs, which are host-side):
    31 -> 32."""
    art = pathlib.Path(__file__).resolve().parents[1] / \
        "experiments" / "graph_audit.json"
    audit = json.loads(art.read_text())
    assert audit["n_programs"] == 32
    assert len(audit["cells"]) == 32


# ---------------------------------------------------------------------------
# Day-in-the-life traces
# ---------------------------------------------------------------------------

def test_make_trace_is_deterministic_and_shaped():
    kw = dict(duration_s=20.0, base_rate=2.0, peak_rate=20.0,
              vocab_size=512, seed=3,
              tenant_mix={"gold": 1, "bronze": 2},
              flash_crowds=((9.0, 11.0, 3.0),))
    t1, t2 = make_trace(**kw), make_trace(**kw)
    assert t1 == t2                       # pure function of its args
    assert all(0 <= ev.at_s < 20.0 for ev in t1)
    assert {ev.spec.tenant for ev in t1} == {"gold", "bronze"}
    # The flash-crowd window is ~3x denser than the same-width window
    # straddling the trough (the trace actually HAS a day shape).
    mid = sum(9.0 <= ev.at_s < 11.0 for ev in t1)
    edge = sum(ev.at_s < 1.0 or ev.at_s >= 19.0 for ev in t1)
    assert mid > 2 * max(1, edge)


def test_make_trace_rejects_junk():
    with pytest.raises(ValueError):
        make_trace(duration_s=0.0, base_rate=1.0, peak_rate=2.0,
                   vocab_size=64)
    with pytest.raises(ValueError):
        make_trace(duration_s=5.0, base_rate=3.0, peak_rate=2.0,
                   vocab_size=64)        # peak below base
    with pytest.raises(ValueError):
        make_trace(duration_s=5.0, base_rate=1.0, peak_rate=2.0,
                   vocab_size=64, flash_crowds=((4.0, 3.0, 2.0),))


def test_run_trace_virtual_clock_drives_autoscaler(model, params):
    """run_trace replays on the fleet-parallel virtual clock: the
    Autoscaler's replica-second integral ticks in TRACE time (bounded
    by capacity x makespan), per-tenant identity holds, and zero SLO
    inversions are recorded."""
    def factory():
        return ServeEngine(model, params,
                           tenant_classes="gold=3,bronze=1", **GEOM)

    trace = make_trace(duration_s=1.5, base_rate=10.0, peak_rate=60.0,
                       vocab_size=1024, seed=5,
                       tenant_mix={"gold": 1, "bronze": 1},
                       prompt_len=(4, 9), max_new=(3, 6))
    router = Router([factory()])
    auto = Autoscaler(router, factory, min_replicas=1, max_replicas=2,
                      up_tokens_per_replica=8.0,
                      down_tokens_per_replica=2.0, hold_steps=2,
                      cooldown_ms=50.0, enabled=True)
    m = run_trace(auto, trace, slo_ttft_ms=1e4,
                  class_weights={"gold": 3, "bronze": 1})
    assert m["n_requests"] == len(trace)
    assert m["accounting_ok"] and m["tenant_accounting_ok"]
    assert m["slo_inversions"] == 0
    assert m["n_completed"] + m["n_shed"] + m["n_cancelled"] \
        == len(trace)
    assert set(m["tenants"]) == {"gold", "bronze"}
    # The controller clock was swapped to trace time: the integral
    # can never exceed max_replicas x virtual makespan.
    assert 0 < m["replica_seconds"] <= 2 * m["makespan_s"] + 1e-6
    assert "autoscale" in m


# ---------------------------------------------------------------------------
# Autoscaler guard rails
# ---------------------------------------------------------------------------

def test_autoscaler_validates_knobs(model, params):
    router = Router([ServeEngine(model, params, **GEOM)])

    def factory():
        return ServeEngine(model, params, **GEOM)

    with pytest.raises(ValueError):
        Autoscaler(router, factory, min_replicas=0)
    with pytest.raises(ValueError):
        Autoscaler(router, factory, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        Autoscaler(router, factory, up_tokens_per_replica=4.0,
                   down_tokens_per_replica=8.0)
    with pytest.raises(ValueError):
        Autoscaler(router, factory, cooldown_ms=0.0)
    with pytest.raises(ValueError):
        Autoscaler(router, factory, hold_steps=0)


def test_autoscaler_hysteresis_and_cooldown(model, params):
    """hold_steps consecutive observations are required to act, and
    the cooldown blocks back-to-back actions on a fake clock."""
    def factory():
        return ServeEngine(model, params, **GEOM)

    clk = [0.0]
    router = Router([factory()])
    auto = Autoscaler(router, factory, min_replicas=1, max_replicas=3,
                      up_tokens_per_replica=2.0,
                      down_tokens_per_replica=0.5, hold_steps=3,
                      cooldown_ms=1000.0, enabled=True,
                      clock=lambda: clk[0])
    for k in range(8):
        auto.submit(_prompt(5, seed=k), 4)
    auto._tick(); auto._tick()
    assert len(router.replicas) == 1      # 2 < hold_steps observations
    auto._tick()
    assert len(router.replicas) == 2      # third consecutive -> act
    auto._tick(); auto._tick(); auto._tick()
    assert len(router.replicas) == 2      # cooldown holds at t=0
    clk[0] = 1.5                          # 1500 ms later
    auto._tick(); auto._tick(); auto._tick()
    assert len(router.replicas) == 3
    auto.run()
    assert auto.accounting_ok()
