"""Live train→serve weight streaming (tpu_ddp/publish/, DESIGN.md §24):
the versioned store's monotonic/rollback contract, wire exactness and
byte reductions, the zero-copy no-retrace version flip, atomic cutover
(token-level parity across a mid-request flip), the staleness gate and
chaos drills, and the closed online-RL round trip where the engine
provably serves trainer-updated weights.

Engines share the fast-tier cache geometry (tests/test_serve.py), so
the memoized decode/prefill programs compile once for the module.
"""

import types
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.publish import (
    PUBLISH_WIRES,
    Publisher,
    StaleVersionError,
    Subscriber,
    VersionedParams,
    attach,
    tree_digests,
)
from tpu_ddp.publish.subscriber import _APPLY
from tpu_ddp.serve import ServeEngine

GEOM = dict(num_slots=4, block_size=8, prefill_chunk=8)


@pytest.fixture(scope="module")
def model():
    return make_transformer("TransformerLM-tiny", max_seq_len=64,
                            compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def _host(tree):
    return jax.tree.map(np.asarray, tree)


def _perturb(tree, eps):
    return jax.tree.map(lambda x: x + np.float32(eps), tree)


def _drain(engine, sub, cap=200):
    for _ in range(cap):
        if not sub.lag:
            return
        engine.step()
    raise AssertionError(f"subscriber still lagging after {cap} steps")


def _state(tree, step):
    return types.SimpleNamespace(params=tree, step=step)


class TestVersionedStore:
    def test_commit_is_strictly_monotonic(self):
        tree = {"w": np.ones(4, np.float32)}
        store = VersionedParams(tree)
        assert store.version == 0 and store.verify()
        nxt = {"w": np.full(4, 2.0, np.float32)}
        store.commit(nxt, 1, nxt)
        assert store.version == 1 and store.last_good_version == 0
        for bad in (1, 0, -3):
            with pytest.raises(StaleVersionError):
                store.commit(nxt, bad, nxt)

    def test_rollback_restores_last_good(self):
        v0 = {"w": np.arange(4, dtype=np.float32)}
        store = VersionedParams(v0)
        d0 = store.digests
        v1 = {"w": np.arange(4, dtype=np.float32) + 1}
        store.commit(v1, 1, v1)
        version, host = store.rollback()
        assert version == 0
        np.testing.assert_array_equal(host["w"], v0["w"])
        assert store.digests == d0 and store.verify()
        with pytest.raises(ValueError):
            store.rollback()   # retention is one-deep


class TestWire:
    def test_full_push_is_exact_and_digests_agree(self, model, params):
        eng = ServeEngine(model, params, **GEOM)
        pub = Publisher(publish_every=1, wire="none", bucket_mb=1)
        sub = attach(pub, eng, name="w")[0]
        update = pub.publish(params=params, step=1)
        assert update.kind == "full" and update.version == 1
        _drain(eng, sub)
        # f32 through the dense wire is exact: the served tree is
        # bitwise the published one, on device and in the host mirror.
        assert tree_digests(_host(eng.params)) == update.digests
        assert sub.store.digests == update.digests
        assert eng.param_version == 1

    def test_delta_trajectory_tracks_and_stays_bitwise_synced(
            self, model, params):
        eng = ServeEngine(model, params, **GEOM)
        pub = Publisher(publish_every=1, wire="none", bucket_mb=1)
        sub = attach(pub, eng, name="d")[0]
        pub.publish(params=params, step=0)
        p = params
        for step in range(1, 4):
            p = _perturb(p, 0.01)
            update = pub.publish(params=p, step=step)
            assert update.kind == "delta"
            _drain(eng, sub)
            # Bitwise publisher<->subscriber at every version...
            assert sub.store.digests == update.digests
            assert tree_digests(_host(eng.params)) == update.digests
        # ...and the reconstruction tracks the raw trajectory (exact
        # equality is not owed — a+(b-a) != b in floats — closeness is).
        for a, b in zip(jax.tree.leaves(sub.store.host),
                        jax.tree.leaves(_host(p))):
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-5)

    def test_lossy_wires_cut_bytes_in_order(self, params):
        host = _host(params)
        sent = {}
        for wire in PUBLISH_WIRES:
            pub = Publisher(publish_every=1, wire=wire, bucket_mb=1)
            pub.publish(params=host, step=0)
            for c in pub._codecs:
                c.reset()          # count the delta trajectory only
            p = host
            for step in range(1, 4):
                p = _perturb(p, 0.001)
                pub.publish(params=p, step=step)
                sent[wire] = pub.stats()["bytes_sent"]
        assert sent["int8"] < sent["bf16"] < sent["none"]

    def test_int8_error_feedback_stays_synced_and_close(
            self, model, params):
        """The lossy wire's contract: publisher reconstruction and
        subscriber land bitwise equal at every version (reconstruction
        tracking), and error feedback keeps the served weights close
        to the raw trained trajectory instead of drifting."""
        eng = ServeEngine(model, params, **GEOM)
        pub = Publisher(publish_every=1, wire="int8", bucket_mb=1)
        sub = attach(pub, eng, name="ef")[0]
        pub.publish(params=params, step=0)
        p = params
        for step in range(1, 5):
            p = _perturb(p, 0.001)
            u = pub.publish(params=p, step=step)
            _drain(eng, sub)
            assert sub.store.digests == u.digests
        raw = _host(p)
        for a, b in zip(jax.tree.leaves(sub.store.host),
                        jax.tree.leaves(raw)):
            np.testing.assert_allclose(a, b, rtol=0, atol=5e-3)

    def test_layout_change_forces_full_push(self, model, params):
        pub = Publisher(publish_every=1, wire="none", bucket_mb=1)
        assert pub.publish(params=params, step=0).kind == "full"
        assert pub.publish(params=params, step=1).kind == "delta"
        other = {"w": np.ones((8, 8), np.float32)}
        assert pub.publish(params=other, step=2).kind == "full"


class TestAtomicSwap:
    def test_flip_does_not_retrace_or_copy(self, model, params,
                                           no_retrace):
        from tpu_ddp.analysis import (donation_report,
                                      runtime_donation_check)

        eng = ServeEngine(model, params, **GEOM)
        pub = Publisher(publish_every=1, wire="none", bucket_mb=1)
        sub = attach(pub, eng, name="nr")[0]
        # Warm every program: full push + one delta flip + a request.
        pub.publish(params=params, step=0)
        _drain(eng, sub)
        pub.publish(params=_perturb(params, 0.01), step=1)
        _drain(eng, sub)
        r = eng.submit([1, 2, 3], 2)
        eng.run()
        # Steady state: further version flips reuse every executable.
        with no_retrace(0, watch=("push_pack", "apply_delta", "step",
                                  "prefill")):
            p = _perturb(params, 0.02)
            for step in range(2, 5):
                pub.publish(params=p, step=step)
                _drain(eng, sub)
                p = _perturb(p, 0.01)
            r = eng.submit([4, 5, 6], 2)
            eng.run()
        assert eng.param_version == 5 and r.done
        # Static donation claim: the staging->live apply aliases the
        # donated live tree (an unaliased donation = full-model copy
        # every flip).
        rep = donation_report(sub.lower_apply_step(), min_bytes=1024)
        assert rep["findings"] == []
        assert rep["donated"], "apply donates nothing?"
        # Runtime claim: the donated buffers are actually REUSED.
        # (jnp.array copy=True: a CPU jnp.asarray of host numpy may
        # alias the numpy buffer, which XLA then cannot donate.)
        live = jax.tree.map(lambda x: jnp.array(x, copy=True),
                            _host(params))
        delta = jax.tree.map(
            lambda x: jnp.zeros(jnp.shape(x), jnp.float32), live)
        findings = runtime_donation_check(_APPLY, live, delta,
                                          min_bytes=1024)
        assert findings == []

    def test_foreign_layout_is_rejected_before_any_flip(self, model,
                                                        params):
        """An update whose bucket layout does not match the engine's
        parameters is dropped loudly — the engine keeps serving."""
        eng = ServeEngine(model, params, **GEOM)
        sub = Subscriber(eng, name="fl")
        other_pub = Publisher(publish_every=1, wire="none", bucket_mb=1)
        u = other_pub.publish(params={"w": np.ones((8, 8), np.float32)},
                              step=0)
        sub.deliver(u)
        with pytest.warns(UserWarning, match="layout"):
            sub.on_engine_step()
        assert sub.rejected == 1 and sub.applied_version == 0
        r = eng.submit([1, 2, 3], 2)
        eng.run()
        assert r.done


class TestAtomicCutover:
    def test_token_parity_across_mid_request_flip(self, model, params):
        """A request overlapping the flip is bitwise identical to the
        runs on the versions each token saw: tokens before the flip
        match the v1 run, tokens after match the v2 continuation, and
        the stamps split exactly [v1]*j + [v2]*(n-j)."""
        prompt = np.arange(1, 7, dtype=np.int64)
        n_new, j = 8, 3
        kw = dict(temperature=0.7, seed=11)

        eng = ServeEngine(model, params, **GEOM)
        # bucket_mb big enough for a single bucket: the flip lands on
        # the first engine step after the publish, deterministically.
        pub = Publisher(publish_every=1, wire="none", bucket_mb=64)
        sub = attach(pub, eng, name="cut")[0]
        pub.publish(params=params, step=0)     # v1 == params (f32 exact)
        _drain(eng, sub)

        # Reference run entirely on v1.
        ref1 = ServeEngine(model, params, **GEOM)
        r1 = ref1.submit(prompt, n_new, **kw)
        ref1.run()

        # The spanning request: j tokens on v1, then the flip.
        rc = eng.submit(prompt, n_new, **kw)
        while len(rc.tokens) < j:
            eng.step()
        pub.publish(params=_perturb(params, 0.01), step=1)   # v2
        eng.run()
        assert rc.done and len(rc.tokens) == n_new
        assert rc.token_versions == [1] * j + [2] * (n_new - j)

        # Prefix parity: what v1 served is what the v1-only run sampled.
        assert rc.tokens[:j] == r1.tokens[:j]
        # Tail parity: continuation on the v2 weights (the engine's own
        # post-flip tree — bitwise what the subscriber committed), with
        # the stateless (seed, position) sampling contract.
        ref2 = ServeEngine(model, sub.store.host, **GEOM)
        r2 = ref2.submit(np.concatenate([prompt.astype(np.int32),
                                         np.asarray(rc.tokens[:j],
                                                    np.int32)]),
                         n_new - j, **kw)
        ref2.run()
        assert rc.tokens[j:] == r2.tokens
        # Deterministic replay: the same spanning run replays bitwise.
        eng2 = ServeEngine(model, params, **GEOM)
        pub2 = Publisher(publish_every=1, wire="none", bucket_mb=64)
        sub2 = attach(pub2, eng2, name="cut2")[0]
        pub2.publish(params=params, step=0)
        _drain(eng2, sub2)
        rr = eng2.submit(prompt, n_new, **kw)
        while len(rr.tokens) < j:
            eng2.step()
        pub2.publish(params=_perturb(params, 0.01), step=1)
        eng2.run()
        assert rr.tokens == rc.tokens
        assert rr.token_versions == rc.token_versions

    def test_loadgen_asserts_cutover_and_reports_versions(
            self, model, params):
        from tpu_ddp.serve.loadgen import (assert_atomic_cutover,
                                           make_workload, run_load)

        eng = ServeEngine(model, params, **GEOM)
        pub = Publisher(publish_every=1, wire="none", bucket_mb=64)
        sub = attach(pub, eng, name="lg")[0]
        pub.publish(params=params, step=0)
        _drain(eng, sub)
        specs = make_workload(6, 1024, seed=3)
        metrics = run_load(eng, specs, rate=500.0, seed=3)
        assert metrics["param_version_min"] == 1
        assert metrics["param_version_max"] == 1
        assert metrics["n_version_spanning"] == 0
        # A decreasing stamp sequence is the bug the assert exists for.
        bad = types.SimpleNamespace(rid=9, tokens=[1, 2],
                                    token_versions=[2, 1])
        with pytest.raises(AssertionError):
            assert_atomic_cutover([bad])
        short = types.SimpleNamespace(rid=9, tokens=[1, 2],
                                      token_versions=[1])
        with pytest.raises(AssertionError):
            assert_atomic_cutover([short])


class TestStalenessAndChaos:
    def test_gate_blocks_then_catches_up(self, model, params):
        eng = ServeEngine(model, params, **GEOM)
        pub = Publisher(publish_every=1, wire="none",
                        max_staleness_steps=1, bucket_mb=1)
        sub = attach(pub, eng, name="g")[0]
        p = params
        for step in range(1, 6):
            p = _perturb(p, 0.01)
            pub.after_step(_state(p, step), step)
        # The gate pumped the attached engine: staleness is bounded...
        assert pub.staleness(5) <= pub.max_staleness_steps
        assert pub.gate_blocks >= 1
        # ...and a drain converges to the final version, nothing lost.
        _drain(eng, sub)
        assert eng.param_version == pub.version == 5
        assert sub.rejected == 0

    def test_publisher_death_keeps_serving_last_good(
            self, model, params, monkeypatch, tmp_path):
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS", "publisher-death@2")
        monkeypatch.setenv("TPU_DDP_CHAOS_SENTINEL", str(tmp_path))
        eng = ServeEngine(model, params, **GEOM)
        pub = Publisher(publish_every=1, wire="none", bucket_mb=1)
        sub = attach(pub, eng, name="pd")[0]
        assert pub.publish(params=params, step=1) is not None
        _drain(eng, sub)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            u2 = pub.publish(params=_perturb(params, 0.5), step=2)
        assert u2 is None and pub.dead and pub.deaths == 1
        assert sub.publisher_lost_n == 1
        assert any("publisher lost" in str(x.message) for x in w)
        # Serving survives on the last-good version, and says so.
        r = eng.submit([1, 2, 3], 3)
        eng.run()
        assert r.done and eng.param_version == 1
        assert r.token_versions == [1, 1, 1]
        # The cadence respects death: no further pushes are attempted.
        assert pub.maybe_publish(_state(params, 3), 3) is None

    def test_push_stall_delays_in_order_and_gates(
            self, model, params, monkeypatch, tmp_path):
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS", "push-stall@2")
        monkeypatch.setenv("TPU_DDP_CHAOS_SENTINEL", str(tmp_path))
        eng = ServeEngine(model, params, **GEOM)
        pub = Publisher(publish_every=1, wire="none",
                        max_staleness_steps=1, bucket_mb=1)
        sub = attach(pub, eng, name="st")[0]
        p = params
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for step in range(1, 5):
                p = _perturb(p, 0.01)
                pub.after_step(_state(p, step), step)
        assert pub.stalls == 1
        assert pub.stall_events == 1 and not pub._stalled
        assert any("stalled" in str(x.message) for x in w)
        # Order held through the stall: nothing rejected, and the
        # engine converges bitwise to the final published version.
        assert sub.rejected == 0
        _drain(eng, sub)
        assert eng.param_version == pub.version == 4
        assert tree_digests(_host(eng.params)) == sub.store.digests


class TestRouterFanout:
    def test_one_publish_reaches_every_replica(self, model, params):
        from tpu_ddp.fleet import Router

        replicas = [ServeEngine(model, params, **GEOM)
                    for _ in range(2)]
        router = Router(replicas)
        pub = Publisher(publish_every=1, wire="none", bucket_mb=1)
        subs = router.subscribe(pub)
        assert len(subs) == 2 and len(pub.subscribers) == 2
        pub.publish(params=_perturb(params, 0.01), step=1)
        for _ in range(200):
            if not any(s.lag for s in subs):
                break
            router.step()
        assert all(r.param_version == 1 for r in replicas)
        d = {tuple(s.store.digests) for s in subs}
        assert len(d) == 1, "replicas diverged"
        for s in router.stats()["replicas"]:
            assert s["param_version"] == 1 and s["publish_lag"] == 0


class TestKnobs:
    def test_env_junk_is_rejected_by_name(self, monkeypatch):
        from tpu_ddp.utils.config import TrainConfig

        for env, junk in (("TPU_DDP_PUBLISH_EVERY", "soon"),
                          ("TPU_DDP_PUBLISH_EVERY", "-2"),
                          ("TPU_DDP_PUBLISH_WIRE", "zstd"),
                          ("TPU_DDP_PUBLISH_MAX_STALENESS", "lots"),
                          ("TPU_DDP_PUBLISH_MAX_STALENESS", "-1")):
            monkeypatch.setenv(env, junk)
            with pytest.raises(ValueError, match=env):
                TrainConfig()
            monkeypatch.delenv(env)

    def test_env_reaches_publisher_defaults(self, monkeypatch):
        from tpu_ddp.utils.config import TrainConfig

        monkeypatch.setenv("TPU_DDP_PUBLISH_EVERY", "4")
        monkeypatch.setenv("TPU_DDP_PUBLISH_WIRE", "int8")
        monkeypatch.setenv("TPU_DDP_PUBLISH_MAX_STALENESS", "2")
        pub = Publisher(config=TrainConfig())
        assert (pub.publish_every, pub.wire,
                pub.max_staleness_steps) == (4, "int8", 2)

    def test_publisher_mirrors_config_validation(self):
        with pytest.raises(ValueError):
            Publisher(publish_every=-1)
        with pytest.raises(ValueError):
            Publisher(wire="zstd")
        with pytest.raises(ValueError):
            Publisher(max_staleness_steps=-1)

    def test_inert_combinations_are_tune_violations(self):
        from tpu_ddp.tune.space import Workload, violations

        ctx = Workload()
        assert violations({"publish_every": 0, "publish_wire": "bf16"},
                          ctx)
        assert violations({"publish_every": 0,
                           "max_staleness_steps": 2}, ctx)
        assert not violations({"publish_every": 4,
                               "publish_wire": "bf16",
                               "max_staleness_steps": 2}, ctx)


class TestClosedLoop:
    def test_engine_provably_serves_trainer_updated_weights(self):
        """The round trip the subsystem exists for: generate → score →
        train → publish, with the served tree bitwise pinned to the
        publisher's reconstruction at every round."""
        from tpu_ddp.ops.optim import SGD
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.publish.rollout import make_prompts, run_online_loop
        from tpu_ddp.train.lm import LMTrainer

        model = make_transformer("TransformerLM-tiny", max_seq_len=64,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(jax.devices()[:2], dp=2)
        trainer = LMTrainer(model, mesh,
                            optimizer=SGD(learning_rate=0.1,
                                          momentum=0.9))
        state = trainer.init_state(seed=3)
        host0 = trainer.params_to_host(state)
        engine = ServeEngine(model, host0, **GEOM)
        d0 = tree_digests(host0)

        pub = Publisher(trainer, publish_every=1, wire="none",
                        bucket_mb=1)
        sub = attach(pub, engine, name="rl")[0]
        prompts = make_prompts(2, 1024, prompt_len=6, seed=0)
        state, report = run_online_loop(
            trainer, engine, pub, state, rounds=2, prompts=prompts,
            max_new_tokens=6, temperature=0.8, samples_per_prompt=2,
            settle_steps=40)
        # Versions advanced and the engine caught up.
        assert pub.version == 2
        assert engine.param_version == 2 and sub.lag == 0
        # The engine serves EXACTLY what the trainer published: equal
        # digests on device params, subscriber mirror, and publisher
        # reconstruction.
        served = tree_digests(_host(engine.params))
        assert served == sub.store.digests
        assert served == tree_digests(
            jax.tree.unflatten(pub._treedef, pub._last))
        # And they are genuinely NEW weights, close to the live state.
        assert served != d0
        for a, b in zip(pub._last,
                        jax.tree.leaves(trainer.params_to_host(state))):
            np.testing.assert_allclose(a, b, rtol=0, atol=1e-5)
        assert report["rounds"][-1]["published_version"] == 2
