"""Resilience subsystem: step guard, checkpoint integrity, chaos
injection, heartbeat watchdog, restart backoff.

The reference has zero failure handling (SURVEY.md §5: no checkpoints,
no failure detection, a dead gloo rank hangs the cluster). These tests
pin the framework's answer layer by layer — the jit-side non-finite
guard, digest-verified checkpoints with quarantine + fallback, the
deterministic fault injector that drills each recovery path, and the
launcher's stall watchdog / backoff schedule. Multi-process drills live
in test_chaos_multiprocess.py.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_ddp.models import get_model
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.resilience.chaos import (FaultInjector, FaultSpec,
                                      chaos_env_active,
                                      corrupt_latest_checkpoint,
                                      parse_faults)
from tpu_ddp.resilience.guard import (StepGuard, TrainingDivergedError,
                                      nonfinite_flag, select_update)
from tpu_ddp.resilience.integrity import (CheckpointCorruptError,
                                          leaf_digest,
                                          quarantine_checkpoint,
                                          restore_newest_verified,
                                          verify_checkpoint)
from tpu_ddp.resilience.watchdog import (STALL_EXIT_CODE,
                                         HeartbeatMonitor,
                                         heartbeat_path, touch_heartbeat)
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils import checkpoint as ckpt
from tpu_ddp.utils.config import TrainConfig
from tpu_ddp.utils.metrics import MetricsLogger


# ---------------------------------------------------------------------------
# Step guard: jit-side pieces


class TestNonfiniteFlag:
    def test_clean_step_not_flagged(self):
        flag = nonfinite_flag(jnp.float32(1.5),
                              {"w": jnp.ones((4,)), "b": jnp.ones(())})
        assert not bool(flag)

    @pytest.mark.parametrize("loss,grad", [
        (np.nan, 1.0), (np.inf, 1.0), (1.0, np.nan), (1.0, np.inf)])
    def test_nonfinite_flagged(self, loss, grad):
        flag = nonfinite_flag(jnp.float32(loss),
                              {"w": jnp.full((4,), grad)})
        assert bool(flag)

    def test_overflowing_square_flagged(self):
        # A finite bf16-ish huge gradient squares to inf in f32 — the
        # guard treats it as non-finite rather than letting the update
        # push params to the overflow region.
        flag = nonfinite_flag(jnp.float32(1.0),
                              {"w": jnp.full((2,), 1e30, jnp.float32)})
        assert bool(flag)

    def test_select_update_keeps_old_when_bad(self):
        old = {"w": jnp.zeros((3,)), "b": jnp.ones(())}
        new = {"w": jnp.ones((3,)), "b": jnp.zeros(())}
        kept = select_update(jnp.bool_(True), old, new)
        np.testing.assert_array_equal(np.asarray(kept["w"]), 0.0)
        taken = select_update(jnp.bool_(False), old, new)
        np.testing.assert_array_equal(np.asarray(taken["w"]), 1.0)


class TestStepGuard:
    def test_streak_resets_on_clean_step(self):
        g = StepGuard(max_bad_steps=2, log=lambda *_: None)
        g.record(0, True, float("nan"))
        g.record(1, False, 1.0)   # resets
        g.record(2, True, float("nan"))
        assert g.consecutive == 1 and g.total_skipped == 2

    def test_raises_after_k_consecutive(self):
        g = StepGuard(max_bad_steps=3, log=lambda *_: None)
        g.record(0, True, float("nan"))
        g.record(1, True, float("nan"))
        with pytest.raises(TrainingDivergedError, match="3 consecutive"):
            g.record(2, True, float("nan"))

    def test_metrics_counter_and_event(self, tmp_path):
        path = tmp_path / "m.jsonl"
        with MetricsLogger(str(path)) as m:
            g = StepGuard(max_bad_steps=10, metrics=m,
                          log=lambda *_: None)
            g.record(5, True, float("inf"))
            assert m.counters["step_skipped"] == 1
        events = [json.loads(l) for l in path.read_text().splitlines()]
        assert events[-1]["event"] == "step_skipped"
        assert events[-1]["step"] == 5

    def test_invalid_k_rejected(self):
        with pytest.raises(ValueError):
            StepGuard(max_bad_steps=0)


# ---------------------------------------------------------------------------
# Step guard: through the Trainer


def _batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 32, 32, 3)).astype(np.float32),
            rng.integers(0, 10, size=n).astype(np.int32))


def _vgg():
    return get_model("VGG11", compute_dtype=np.float32)


class TestGuardedTrainer:
    @pytest.mark.parametrize("strategy,use_mesh", [
        ("none", False),
        # the sharded variants add only layout on top of the guard logic
        # the fast unsharded variant already pins down.
        pytest.param("all_reduce", True, marks=pytest.mark.slow),
        pytest.param("zero", True, marks=pytest.mark.slow)])
    def test_nan_batch_is_exact_noop(self, devices, strategy, use_mesh):
        """A poisoned batch leaves params AND optimizer state bitwise
        unchanged (momentum included), and the next healthy step runs."""
        x, y = _batch()
        mesh = make_mesh(devices[:4]) if use_mesh else None
        tr = Trainer(_vgg(), TrainConfig(), strategy=strategy, mesh=mesh)
        state = tr.init_state()
        xb, yb, wb = tr.put_batch(x, y)
        state, _ = tr.train_step(state, xb, yb, wb)
        assert not tr.last_step_skipped()
        before = jax.device_get({"p": state.params, "o": state.opt_state})
        xn, yn, wn = tr.put_batch(np.full_like(x, np.nan), y)
        state, _ = tr.train_step(state, xn, yn, wn)
        assert tr.last_step_skipped()
        after = jax.device_get({"p": state.params, "o": state.opt_state})
        for a, b in zip(jax.tree.leaves(before), jax.tree.leaves(after)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        state, loss = tr.train_step(state, xb, yb, wb)
        assert not tr.last_step_skipped()
        assert np.all(np.isfinite(np.asarray(loss)))

    def test_guard_off_propagates(self, devices):
        """TPU_DDP_GUARD=0 semantics: the unguarded step trains on the
        poison — proving the guard (not luck) provides the protection."""
        x, y = _batch()
        tr = Trainer(_vgg(), TrainConfig(guard_nonfinite=False),
                     strategy="none")
        state = tr.init_state()
        xn, yn, wn = tr.put_batch(np.full_like(x, np.nan), y)
        state, _ = tr.train_step(state, xn, yn, wn)
        assert not tr.last_step_skipped()
        leaves = jax.tree.leaves(jax.device_get(state.params))
        assert any(not np.all(np.isfinite(np.asarray(l)))
                   for l in leaves)

    @pytest.mark.slow  # full train_epoch over a real trainer; the skip
    # accounting is also asserted cross-process by the nan-grad chaos drill
    def test_epoch_counts_skips_in_metrics(self, devices, tmp_path):
        """train_epoch accounting: one poisoned batch in the stream →
        one step_skipped event, run completes, streak resets."""
        x, y = _batch()
        metrics = MetricsLogger(str(tmp_path / "m.jsonl"))
        cfg = TrainConfig(global_batch_size=8, guard_max_bad_steps=3)
        tr = Trainer(_vgg(), cfg, strategy="fused",
                     mesh=make_mesh(devices[:4]), metrics=metrics)
        state = tr.init_state()
        batches = [(x, y), (np.full_like(x, np.nan), y), (x, y)]
        state, stats = tr.train_epoch(state, batches,
                                      log=lambda *_: None)
        assert stats["iters"] == 3
        assert metrics.counters["step_skipped"] == 1
        assert tr.guard.consecutive == 0  # healthy step after the skip

    @pytest.mark.slow  # raise-after-k is pinned fast at the unit level
    # (test_raises_after_k_consecutive) and the epoch/guard integration
    # by test_epoch_counts_skips_in_metrics; this composes the two
    def test_epoch_raises_after_k_bad_steps(self, devices):
        x, y = _batch()
        cfg = TrainConfig(global_batch_size=8, guard_max_bad_steps=2)
        tr = Trainer(_vgg(), cfg, strategy="fused",
                     mesh=make_mesh(devices[:4]))
        state = tr.init_state()
        nan_batches = [(np.full_like(x, np.nan), y)] * 4
        with pytest.raises(TrainingDivergedError):
            tr.train_epoch(state, nan_batches, log=lambda *_: None)


# ---------------------------------------------------------------------------
# Checkpoint integrity


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": rng.normal(size=(8, 4)).astype(np.float32),
            "b": rng.normal(size=(4,)).astype(np.float32),
            "step": np.int64(seed)}


class TestIntegrity:
    def test_leaf_digest_is_bitwise(self):
        a = np.ones((4, 4), np.float32)
        b = a.copy()
        assert leaf_digest(a) == leaf_digest(b)
        b[2, 2] = np.nextafter(b[2, 2], 2.0)  # one-ulp flip
        assert leaf_digest(a) != leaf_digest(b)

    def test_save_writes_digests_and_verify_passes(self, tmp_path):
        path = ckpt.save_checkpoint(str(tmp_path), _tree(), step=1)
        with open(os.path.join(path, "manifest.json")) as f:
            manifest = json.load(f)
        assert len(manifest["digests"]) == len(manifest["leaves"])
        assert verify_checkpoint(path) == len(manifest["leaves"])

    def test_predigest_manifest_verifies_vacuously(self, tmp_path):
        path = ckpt.save_checkpoint(str(tmp_path), _tree(), step=1)
        mf = os.path.join(path, "manifest.json")
        with open(mf) as f:
            manifest = json.load(f)
        del manifest["digests"]
        with open(mf, "w") as f:
            json.dump(manifest, f)
        assert verify_checkpoint(path) == 0  # old format: no evidence
        restored, step = ckpt.restore_checkpoint(str(tmp_path), _tree())
        assert step == 1

    def test_truncated_npz_raises_corrupt_error(self, tmp_path):
        """Satellite (a): a truncated arrays.npz surfaces as a clear
        CheckpointCorruptError naming the path — not a bare zlib/zipfile
        traceback."""
        ckpt.save_checkpoint(str(tmp_path), _tree(), step=2)
        mangled = corrupt_latest_checkpoint(str(tmp_path))
        assert mangled and mangled.endswith("arrays.npz")
        path = os.path.join(str(tmp_path), "step_00000002")
        with pytest.raises(CheckpointCorruptError) as ei:
            verify_checkpoint(path)
        assert ei.value.path == path
        with pytest.raises(CheckpointCorruptError) as ei:
            ckpt.restore_checkpoint(str(tmp_path), _tree())
        assert ei.value.path == path
        assert "step_00000002" in str(ei.value)

    def test_bitflip_detected_on_restore(self, tmp_path):
        """A same-length content change (np.savez rewrite with one
        element off) defeats size checks but not the digests."""
        tree = _tree()
        path = ckpt.save_checkpoint(str(tmp_path), tree, step=1)
        npz_path = os.path.join(path, "arrays.npz")
        with np.load(npz_path) as npz:
            arrays = {k: npz[k].copy() for k in npz.files}
        key = next(k for k in arrays if k.endswith("w"))
        arrays[key][0, 0] += 1.0
        with open(npz_path, "wb") as f:
            np.savez(f, **arrays)
        with pytest.raises(CheckpointCorruptError, match="digest"):
            verify_checkpoint(path)
        with pytest.raises(CheckpointCorruptError, match="digest"):
            ckpt.restore_checkpoint(str(tmp_path), _tree())

    def test_quarantine_renames_never_deletes(self, tmp_path):
        path = ckpt.save_checkpoint(str(tmp_path), _tree(), step=3)
        q = quarantine_checkpoint(path)
        assert q == path + ".corrupt" and os.path.isdir(q)
        assert not os.path.exists(path)
        # Name collision (a second corrupt step 3): numbered suffix.
        path2 = ckpt.save_checkpoint(str(tmp_path), _tree(), step=3)
        q2 = quarantine_checkpoint(path2)
        assert q2 == path + ".corrupt-2" and os.path.isdir(q2)

    def test_restore_falls_back_to_verified(self, tmp_path):
        """The acceptance drill: newest checkpoint corrupt → restore
        returns the previous verified one and quarantines the corpse."""
        ckpt.save_checkpoint(str(tmp_path), _tree(seed=1), step=1)
        ckpt.save_checkpoint(str(tmp_path), _tree(seed=2), step=2)
        corrupt_latest_checkpoint(str(tmp_path))
        logs = []
        restored, step = restore_newest_verified(
            str(tmp_path), _tree(), log=logs.append)
        assert step == 1
        np.testing.assert_array_equal(restored["w"], _tree(seed=1)["w"])
        assert os.path.isdir(
            os.path.join(str(tmp_path), "step_00000002.corrupt"))
        assert any("quarantined" in l for l in logs)

    def test_all_corrupt_raises(self, tmp_path):
        ckpt.save_checkpoint(str(tmp_path), _tree(), step=1)
        corrupt_latest_checkpoint(str(tmp_path))
        with pytest.raises(CheckpointCorruptError):
            restore_newest_verified(str(tmp_path), _tree(),
                                    log=lambda *_: None)
        # The corpse was quarantined, not deleted.
        assert os.path.isdir(
            os.path.join(str(tmp_path), "step_00000001.corrupt"))

    def test_no_checkpoints_raises_filenotfound(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            restore_newest_verified(str(tmp_path), _tree(),
                                    log=lambda *_: None)

    def test_quarantined_dirs_not_listed(self, tmp_path):
        ckpt.save_checkpoint(str(tmp_path), _tree(), step=1)
        quarantine_checkpoint(
            os.path.join(str(tmp_path), "step_00000001"))
        assert ckpt.all_steps(str(tmp_path)) == []


class TestTrainerRestoreFallback:
    @pytest.mark.slow  # end-to-end trainer compile; the fallback logic is
    # covered fast by TestIntegrity and by the corrupt-ckpt chaos drill
    def test_trainer_restores_previous_verified(self, devices, tmp_path):
        """End-to-end: Trainer saves steps 1 and 2, step 2's npz gets
        truncated, restore_checkpoint comes back at step 1 with the
        corrupt dir quarantined."""
        x, y = _batch()
        tr = Trainer(_vgg(), TrainConfig(global_batch_size=8),
                     strategy="fused", mesh=make_mesh(devices[:4]))
        state = tr.init_state()
        xb, yb, wb = tr.put_batch(x, y)
        state, _ = tr.train_step(state, xb, yb, wb)
        tr.save_checkpoint(str(tmp_path), state)
        state, _ = tr.train_step(state, xb, yb, wb)
        tr.save_checkpoint(str(tmp_path), state)
        corrupt_latest_checkpoint(str(tmp_path))
        restored = tr.restore_checkpoint(str(tmp_path))
        assert restored.step == 1
        assert os.path.isdir(
            os.path.join(str(tmp_path), "step_00000002.corrupt"))


# ---------------------------------------------------------------------------
# Chaos harness


class TestChaosParsing:
    def test_step_and_rank(self):
        specs = parse_faults("nan-grad@3:rank=1, hard-exit@5")
        assert specs == [FaultSpec("nan-grad", step=3, rank=1),
                         FaultSpec("hard-exit", step=5)]

    def test_prob_mode(self):
        (s,) = parse_faults("slow-rank@p0.25")
        assert s.prob == 0.25 and s.step is None

    @pytest.mark.parametrize("bad", [
        "nan-grad", "typo-fault@3", "nan-grad@x", "nan-grad@p2.0",
        "nan-grad@3:rank=x", "nan-grad@3:nodes=2"])
    def test_malformed_rejected(self, bad):
        with pytest.raises(ValueError):
            parse_faults(bad)

    def test_spec_needs_exactly_one_trigger(self):
        with pytest.raises(ValueError):
            FaultSpec("nan-grad")
        with pytest.raises(ValueError):
            FaultSpec("nan-grad", step=1, prob=0.5)

    def test_env_active_gate(self, monkeypatch):
        monkeypatch.delenv("TPU_DDP_CHAOS_FAULTS", raising=False)
        monkeypatch.delenv("TPU_DDP_FAIL_AT_STEP", raising=False)
        assert not chaos_env_active()
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS", "nan-grad@1")
        assert chaos_env_active()


class TestFaultInjector:
    def test_inactive_without_specs(self):
        inj = FaultInjector([], rank=0)
        assert not inj.active
        assert inj.before_step(1) is False

    def test_exact_step_and_rank_targeting(self):
        inj = FaultInjector(parse_faults("nan-grad@3:rank=1"), rank=1)
        assert not inj.before_step(2)
        assert inj.before_step(3)
        other = FaultInjector(parse_faults("nan-grad@3:rank=1"), rank=0)
        assert not other.before_step(3)

    def test_seeded_probabilistic_replay(self):
        """The fire/no-fire sequence is a pure function of (seed, kind,
        step): two injectors with the same seed agree step-for-step, a
        different seed produces a different (but equally deterministic)
        sequence."""
        def seq(seed):
            inj = FaultInjector(parse_faults("nan-grad@p0.3"),
                                seed=seed, rank=0)
            return [inj._fires(inj.specs[0], s) for s in range(200)]
        a, b, c = seq(7), seq(7), seq(8)
        assert a == b
        assert a != c
        assert 20 < sum(a) < 120  # p=0.3 over 200 steps, loose bounds

    def test_sentinel_suppresses_refire(self, tmp_path):
        spec = "nan-grad@2"
        inj = FaultInjector(parse_faults(spec), rank=0,
                            sentinel_dir=str(tmp_path))
        assert inj.before_step(2) is True       # fires, drops marker
        assert inj.before_step(2) is False      # restart replay: blocked
        fresh = FaultInjector(parse_faults(spec), rank=0,
                              sentinel_dir=str(tmp_path))
        assert fresh.before_step(2) is False    # across processes too

    def test_slow_rank_persistent_and_unmarked(self, tmp_path):
        inj = FaultInjector(parse_faults("slow-rank@2"), rank=0,
                            sentinel_dir=str(tmp_path), slow_s=0.0)
        assert not inj.before_step(1)
        inj.before_step(2)
        inj.before_step(5)  # still slow at every later step
        assert os.listdir(str(tmp_path)) == []  # never sentinels

    def test_poison_images_floats_and_ints(self):
        out = FaultInjector.poison_images(np.ones((2, 3), np.float32))
        assert out.dtype == np.float32 and np.all(np.isnan(out))
        out = FaultInjector.poison_images(np.ones((2, 3), np.uint8))
        assert np.issubdtype(out.dtype, np.floating)
        assert np.all(np.isnan(out))

    def test_corrupt_latest_handles_empty(self, tmp_path):
        assert corrupt_latest_checkpoint(str(tmp_path)) is None
        assert corrupt_latest_checkpoint(None) is None

    def test_from_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS",
                           "hard-exit@4,slow-rank@p0.1:rank=2")
        monkeypatch.setenv("TPU_DDP_CHAOS_SEED", "11")
        monkeypatch.setenv("TPU_DDP_CHAOS_SENTINEL", str(tmp_path))
        inj = FaultInjector.from_env(rank=0)
        assert inj.active and inj.seed == 11
        assert inj.sentinel_dir == str(tmp_path)
        assert [s.kind for s in inj.specs] == ["hard-exit", "slow-rank"]


class TestChaosEngineIntegration:
    @pytest.mark.slow  # full train_epoch compile; the same path runs
    # cross-process in test_chaos_multiprocess and scripts/chaos_sweep.py
    def test_nan_grad_injection_skips_step(self, devices, tmp_path,
                                           monkeypatch):
        """The full in-process loop: env-configured nan-grad at step 2
        poisons the batch, the guard skips it, metrics record it, the
        epoch finishes, the sentinel suppresses a refire."""
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS", "nan-grad@2")
        monkeypatch.setenv("TPU_DDP_CHAOS_SENTINEL",
                           str(tmp_path / "sentinels"))
        x, y = _batch()
        metrics = MetricsLogger(str(tmp_path / "m.jsonl"))
        tr = Trainer(_vgg(), TrainConfig(global_batch_size=8),
                     strategy="fused", mesh=make_mesh(devices[:4]),
                     metrics=metrics)
        state = tr.init_state()
        state, stats = tr.train_epoch(state, [(x, y)] * 3,
                                      log=lambda *_: None)
        assert stats["iters"] == 3
        assert metrics.counters.get("step_skipped") == 1
        assert np.all(np.isfinite(
            np.asarray(jax.tree.leaves(jax.device_get(state.params))[0])))
        # Replayed epoch (elastic restart analogue): sentinel blocks.
        state2, _ = tr.train_epoch(state, [(x, y)] * 2,
                                   log=lambda *_: None)
        assert metrics.counters.get("step_skipped") == 1


# ---------------------------------------------------------------------------
# Watchdog + backoff


class TestHeartbeat:
    def test_touch_and_read(self, tmp_path):
        touch_heartbeat(str(tmp_path), 0, step=7)
        p = heartbeat_path(str(tmp_path), 0)
        assert os.path.exists(p)
        assert open(p).read().strip() == "7"

    def test_touch_swallows_oserror(self, tmp_path):
        touch_heartbeat(str(tmp_path / "missing" / "dir"), 0, step=1)

    def test_grace_before_first_beat(self, tmp_path):
        mon = HeartbeatMonitor(str(tmp_path), nproc=2, timeout=0.001)
        assert mon.newest_beat() is None
        assert not mon.stalled()  # silent until a beat exists

    def test_stall_detection_uses_newest(self, tmp_path):
        mon = HeartbeatMonitor(str(tmp_path), nproc=2, timeout=10.0)
        touch_heartbeat(str(tmp_path), 0, step=1)
        touch_heartbeat(str(tmp_path), 1, step=1)
        newest = mon.newest_beat()
        assert not mon.stalled(now=newest + 5.0)
        assert mon.stalled(now=newest + 10.5)
        # One rank beating keeps the cluster alive (straggler != stall).
        touch_heartbeat(str(tmp_path), 1, step=2)
        assert not mon.stalled(now=mon.newest_beat() + 5.0)

    def test_invalid_timeout(self, tmp_path):
        with pytest.raises(ValueError):
            HeartbeatMonitor(str(tmp_path), nproc=1, timeout=0)

    def test_stalled_ranks_names_the_wedged_rank(self, tmp_path):
        # The pre-elastic monitor only compared the NEWEST beat to the
        # deadline: one wedged rank among beating peers was invisible.
        # Per-rank detection must name exactly the silent rank — and
        # one wedged rank now trips the boolean summary too.
        from tpu_ddp.resilience.watchdog import heartbeat_path
        mon = HeartbeatMonitor(str(tmp_path), nproc=3, timeout=10.0)
        for r in range(3):
            touch_heartbeat(str(tmp_path), r, step=1)
        base = mon.newest_beat()
        p1 = heartbeat_path(str(tmp_path), 1)
        os.utime(p1, (base - 60.0, base - 60.0))
        assert mon.stalled_ranks(now=base + 5.0) == [1]
        assert mon.stalled(now=base + 5.0)

    def test_never_beaten_rank_measured_from_first_beat(self, tmp_path):
        mon = HeartbeatMonitor(str(tmp_path), nproc=2, timeout=10.0)
        touch_heartbeat(str(tmp_path), 0, step=1)
        first = mon.newest_beat()
        # Rank 1 never beat: it gets one full timeout of compile skew
        # from the cluster's first beat, then both ranks are stale.
        assert mon.stalled_ranks(now=first + 9.0) == []
        assert mon.stalled_ranks(now=first + 10.5) == [0, 1]

    def test_ranks_filter_ignores_departed(self, tmp_path):
        # The elastic launcher restricts the check to live membership:
        # a departed rank's stale heartbeat file must not re-trip.
        from tpu_ddp.resilience.watchdog import heartbeat_path
        mon = HeartbeatMonitor(str(tmp_path), nproc=2, timeout=10.0)
        for r in (0, 1):
            touch_heartbeat(str(tmp_path), r, step=3)
        base = mon.newest_beat()
        os.utime(heartbeat_path(str(tmp_path), 1),
                 (base - 60.0, base - 60.0))
        assert mon.stalled_ranks(now=base + 1.0) == [1]
        assert mon.stalled_ranks(now=base + 1.0, ranks=[0]) == []

    def test_reset_grace_covers_reshard_recompile(self, tmp_path):
        # After a membership epoch every survivor legitimately pauses
        # beating to recompile; reset_grace restarts all clocks.
        mon = HeartbeatMonitor(str(tmp_path), nproc=2, timeout=10.0)
        for r in (0, 1):
            touch_heartbeat(str(tmp_path), r, step=3)
        base = mon.newest_beat()
        assert mon.stalled_ranks(now=base + 60.0) == [0, 1]
        mon.reset_grace(now=base + 60.0)
        assert mon.stalled_ranks(now=base + 65.0) == []
        assert mon.stalled_ranks(now=base + 71.0) == [0, 1]

    def test_exit_codes_distinct(self):
        from tpu_ddp.resilience.chaos import FAULT_EXIT_CODE
        assert STALL_EXIT_CODE != FAULT_EXIT_CODE
        assert STALL_EXIT_CODE not in (0, -9)


class TestBackoff:
    def test_deterministic_with_injected_rng(self):
        import random

        from tpu_ddp.launch import backoff_delay
        a = [backoff_delay(i, floor=1.0, rng=random.Random(3))
             for i in range(1, 6)]
        b = [backoff_delay(i, floor=1.0, rng=random.Random(3))
             for i in range(1, 6)]
        assert a == b

    def test_exponential_doubling_capped(self):
        import random

        from tpu_ddp.launch import backoff_delay
        rng = random.Random(0)

        class NoJitter(random.Random):
            def uniform(self, a, b):
                return 0.0
        nj = NoJitter()
        delays = [backoff_delay(i, floor=1.0, cap=8.0, rng=nj)
                  for i in range(1, 7)]
        assert delays == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]
        # Jitter adds at most 25%.
        assert backoff_delay(1, floor=1.0, rng=rng) <= 1.25

    def test_floor_zero_disables(self):
        from tpu_ddp.launch import backoff_delay
        assert backoff_delay(3, floor=0.0) == 0.0

    def test_attempt_is_one_based(self):
        from tpu_ddp.launch import backoff_delay
        with pytest.raises(ValueError):
            backoff_delay(0)

    def test_restart_window_frees_budget(self, monkeypatch):
        """Sliding-window budget: stamps older than the window age out,
        so max_restarts bounds the restart RATE, not the lifetime count.
        Driven through launch_elastic with a stubbed launch."""
        import tpu_ddp.launch as launch_mod

        fails = iter([True, True, False])
        clock = {"t": 0.0}

        def fake_launch(part, nproc, extra_args=None, **kw):
            clock["t"] += 100.0  # each attempt runs 100 s before failing
            res = launch_mod.LaunchResult(
                workers=[launch_mod.WorkerResult(0, 0)])
            res.first_failure = 13 if next(fails) else 0
            return res

        monkeypatch.setattr(launch_mod, "launch", fake_launch)
        monkeypatch.setattr(launch_mod.time, "monotonic",
                            lambda: clock["t"])
        monkeypatch.setattr(launch_mod.time, "sleep",
                            lambda s: clock.__setitem__("t",
                                                        clock["t"] + s))
        res = launch_mod.launch_elastic(
            "part3", nproc=1, max_restarts=1, restart_window=50.0,
            min_restart_interval=0.0)
        # Each restart's stamp ages out of the 50 s window during the
        # next 100 s attempt, so a budget of 1 sustains 2 restarts —
        # more than the lifetime cap would allow — and the run recovers.
        assert res.ok
        assert res.restarts == 2

    def test_lifetime_budget_still_stops(self, monkeypatch):
        import tpu_ddp.launch as launch_mod

        def always_fail(part, nproc, extra_args=None, **kw):
            res = launch_mod.LaunchResult(
                workers=[launch_mod.WorkerResult(0, 13)])
            res.first_failure = 13
            return res

        monkeypatch.setattr(launch_mod, "launch", always_fail)
        res = launch_mod.launch_elastic(
            "part3", nproc=1, max_restarts=2, min_restart_interval=0.0)
        assert not res.ok
        assert res.restarts == 2
