"""Mixture-of-experts + expert parallelism: the ep-sharded MoE computes
the same function as its single-device execution (drop-free capacity),
the router is differentiable, and MoE composes with dp and pp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.optim import SGD
from tpu_ddp.parallel.mesh import EXPERT_AXIS, make_mesh
from tpu_ddp.parallel.moe import switch_route
from tpu_ddp.train.lm import (LMTrainer, PipelineLMTrainer, make_lm_batch)


def _moe(**kw):
    cfg = dict(max_seq_len=32, compute_dtype=jnp.float32,
               moe_capacity_factor=8.0)  # drop-free for equivalence tests
    cfg.update(kw)
    return make_transformer("TransformerLM-moe-tiny", **cfg)


def _sgd():
    return SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)


def _tokens(b=4, L=33, seed=11):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 1024, size=(b, L))


def _one_moe_step(devices, dp, ep, tokens, **model_kw):
    """One SGD step of a MoE LM on a dp x ep mesh; returns (params,
    mean loss). Shared by the top-1 and top-2 equivalence tests."""
    model = _moe(**model_kw)
    mesh = make_mesh(devices[:dp * ep], dp=dp, sp=1, mp=1, pp=1, ep=ep)
    tr = LMTrainer(model, mesh, optimizer=_sgd())
    state = tr.init_state(seed=3)
    x, y = tr.put_batch(*make_lm_batch(tokens))
    state, loss = tr.train_step(state, x, y)
    return jax.device_get(state.params), float(np.mean(np.asarray(loss)))


class TestSwitchRouting:
    def test_dispatch_shapes_and_capacity(self):
        logits = jnp.asarray(np.random.default_rng(0).normal(
            size=(16, 4)).astype(np.float32))
        dispatch, combine, aux = switch_route(logits, 4, capacity=2)
        assert dispatch.shape == (16, 4, 2)
        # At most `capacity` tokens per expert slot column.
        assert float(jnp.max(jnp.sum(dispatch, axis=(0, 2)))) <= 2 + 1e-6
        # Each kept token occupies exactly one (expert, slot).
        per_token = jnp.sum(dispatch, axis=(1, 2))
        assert set(np.unique(np.asarray(per_token))) <= {0.0, 1.0}
        assert np.isfinite(float(aux))

    def test_balanced_routing_aux_is_one(self):
        # Perfectly uniform router -> f_e = P_e = 1/E -> aux = E*E*(1/E^2).
        logits = jnp.zeros((8, 4), jnp.float32)
        _, _, aux = switch_route(logits, 4, capacity=8)
        assert abs(float(aux) - 1.0) < 1e-5


class TestTopKRouting:
    def _route(self, T=16, E=4, C=32, k=2, seed=0):
        from tpu_ddp.parallel.moe import topk_route
        logits = jnp.asarray(np.random.default_rng(seed).normal(
            size=(T, E)).astype(np.float32))
        return topk_route(logits, E, C, top_k=k)

    def test_top2_two_assignments_per_token(self):
        dispatch, combine, aux = self._route()
        per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
        # Generous capacity: every token keeps both its choices.
        np.testing.assert_array_equal(per_token, 2.0)
        # Each (expert, slot) pair holds at most one token.
        per_slot = np.asarray(jnp.sum(dispatch, axis=0))
        assert per_slot.max() <= 1.0 + 1e-6
        assert np.isfinite(float(aux))

    def test_top2_gates_normalized(self):
        dispatch, combine, _ = self._route()
        # Kept tokens' combine weights sum to ~1 over their two slots.
        w = np.asarray(jnp.sum(combine, axis=(1, 2)))
        np.testing.assert_allclose(w, 1.0, rtol=1e-5)

    def test_top1_reduces_to_switch(self):
        from tpu_ddp.parallel.moe import switch_route, topk_route
        logits = jnp.asarray(np.random.default_rng(3).normal(
            size=(16, 4)).astype(np.float32))
        d1, c1, a1 = switch_route(logits, 4, 8)
        d2, c2, a2 = topk_route(logits, 4, 8, top_k=1)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
        assert float(a1) == float(a2)

    def test_top2_ep_sharded_step_matches_unsharded(self, devices):
        """The ep equivalence holds for k=2 routing too."""
        tokens = _tokens(seed=21)
        ref_p, ref_loss = _one_moe_step(devices, 4, 1, tokens,
                                        moe_top_k=2)
        got_p, got_loss = _one_moe_step(devices, 1, 4, tokens,
                                        moe_top_k=2)
        assert abs(got_loss - ref_loss) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)

    def test_top_k_validation(self):
        from tpu_ddp.parallel.moe import topk_route
        logits = jnp.zeros((8, 4), jnp.float32)
        with pytest.raises(ValueError, match="top_k"):
            topk_route(logits, 4, 8, top_k=0)
        with pytest.raises(ValueError, match="top_k"):
            topk_route(logits, 4, 8, top_k=5)


class TestMoEForward:
    def test_apply_with_aux(self):
        model = _moe()
        params = model.init(jax.random.key(0))
        tokens = jnp.asarray(_tokens(2, 17)[:, :16])
        logits, aux = model.apply_with_aux(params, tokens)
        assert logits.shape == (2, 16, model.vocab_size)
        assert float(aux) > 0.0
        # Dense model reports zero aux.
        dense = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        dp = dense.init(jax.random.key(0))
        _, dense_aux = dense.apply_with_aux(dp, tokens)
        assert float(dense_aux) == 0.0

    def test_router_gradient_nonzero(self):
        model = _moe()
        params = model.init(jax.random.key(1))
        tokens = jnp.asarray(_tokens(2, 17)[:, :16])

        def loss(p):
            logits, aux = model.apply_with_aux(p, tokens)
            return jnp.mean(logits ** 2) + 0.01 * aux

        grads = jax.grad(loss)(params)
        g_router = np.asarray(grads["blocks"][0]["router"])
        assert np.abs(g_router).max() > 0.0


class TestExpertParallelEquivalence:
    @pytest.mark.parametrize("dp,ep", [
        # (1,4) only widens the expert axis (1,2) already pins.
        pytest.param(1, 4, marks=pytest.mark.slow),
        # dp x ep mixing is covered by (1,2)+(1,4) against the pure-ep
        # cells; (2,2) adds only one more mesh layout compile
        pytest.param(2, 2, marks=pytest.mark.slow),
        (1, 2)])
    def test_step_matches_unsharded(self, devices, dp, ep):
        tokens = _tokens()
        ref_p, ref_loss = _one_moe_step(devices, dp * ep, 1, tokens)
        got_p, got_loss = _one_moe_step(devices, dp, ep, tokens)
        assert abs(got_loss - ref_loss) < 1e-4, (dp, ep)
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=3e-4, atol=3e-5,
                err_msg=f"dp={dp} ep={ep}")

    def test_loss_decreases_with_drops(self, devices):
        """Tight capacity (tokens dropped) still trains stably."""
        model = _moe(moe_capacity_factor=0.5)
        mesh = make_mesh(devices[:4], dp=2, sp=1, mp=1, pp=1, ep=2)
        tr = LMTrainer(model, mesh)
        state = tr.init_state()
        x, y = tr.put_batch(*make_lm_batch(_tokens(b=4)))
        losses = []
        for _ in range(3):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestMoEComposition:
    def test_moe_under_pipeline(self, devices):
        """MoE blocks run under pp (experts stage-local, aux discarded)."""
        model = _moe()
        mesh = make_mesh(devices[:4], dp=2, sp=1, mp=1, pp=2)
        tr = PipelineLMTrainer(model, mesh, num_micro=2, optimizer=_sgd())
        state = tr.init_state(seed=0)
        x, y = tr.put_batch(*make_lm_batch(_tokens(b=4)))
        state, loss = tr.train_step(state, x, y)
        assert np.isfinite(float(np.mean(np.asarray(loss))))

    def _one_pp_step(self, devices, dp, ep, tokens, schedule="gpipe",
                     opt_sharding="replicated", steps=1):
        """One (or more) SGD steps of the MoE LM under pp=2 x dp x ep."""
        mesh = make_mesh(devices[:dp * 2 * ep], dp=dp, sp=1, mp=1, pp=2,
                         ep=ep)
        tr = PipelineLMTrainer(_moe(), mesh, num_micro=2,
                               optimizer=_sgd(), schedule=schedule,
                               opt_sharding=opt_sharding)
        state = tr.init_state(seed=3)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        loss = None
        for _ in range(steps):
            state, loss = tr.train_step(state, x, y)
        return (jax.device_get(state.params),
                float(np.mean(np.asarray(loss))))

    @pytest.mark.parametrize("schedule", [
        # gpipe adds only the other schedule's compile on the same cell
        pytest.param("gpipe", marks=pytest.mark.slow), "1f1b"])
    def test_pp_ep_matches_stage_local(self, devices, schedule):
        """pp x ep (round-5): experts shard over ep WITHIN each stage
        (the MoE all_to_all rides inside the stage's blocks, orthogonal
        to the stage ring). Exact vs pp with stage-local full experts at
        the same total token sharding (dp x ep folded into dp) — the
        same equivalence contract the dense-trainer ep tests pin."""
        tokens = _tokens(b=8)
        ref_p, ref_l = self._one_pp_step(devices, 4, 1, tokens, schedule)
        got_p, got_l = self._one_pp_step(devices, 2, 2, tokens, schedule)
        assert abs(got_l - ref_l) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5,
                                       err_msg=schedule)

    @pytest.mark.slow  # two two-step 8-device pp x ep runs; the
    # one-step pp x ep exactness above stays in the default tier
    def test_pp_ep_zero1_matches_replicated_opt(self, devices):
        """pp x ep x ZeRO-1: stacked expert leaves' optimizer state lays
        out P((pp, ep, dp)) and the two-step update (momentum through
        the scattered layout) matches the replicated optimizer."""
        from jax.sharding import PartitionSpec as P
        from tpu_ddp.parallel.mesh import DATA_AXIS, PIPE_AXIS
        tokens = _tokens(b=8)
        ref_p, ref_l = self._one_pp_step(devices, 2, 2, tokens, steps=2)
        got_p, got_l = self._one_pp_step(devices, 2, 2, tokens, steps=2,
                                         opt_sharding="zero1")
        assert abs(got_l - ref_l) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)
        # Pin the three-axis state layout on the expert leaves.
        mesh = make_mesh(devices[:8], dp=2, sp=1, mp=1, pp=2, ep=2)
        tr = PipelineLMTrainer(_moe(), mesh, num_micro=2,
                               optimizer=_sgd(), opt_sharding="zero1")
        mom = tr.init_state(seed=0).opt_state["momentum"]
        w1 = mom["blocks"]["w1"]  # stacked (L, E, dm, dff), pp x ep
        assert w1.sharding.spec == P((PIPE_AXIS, EXPERT_AXIS, DATA_AXIS))
        assert w1.addressable_shards[0].data.size == w1.size // 8

    @pytest.mark.slow  # two 8-device MoE compiles; the pairwise cells
    # cover the semantics in the default tier
    def test_four_axis_matches_folded(self, devices):
        """The full dense-trainer matrix in ONE cell: sp x tp x ep
        (round-5 coverage pin — each pairwise composition was exact-
        tested, this pins the triple). Exact vs the same token sharding
        with ep folded into dp (the ep equivalence contract), both on
        sp=2 x mp=2."""
        model = _moe()
        tokens = _tokens(b=8)

        def run(dp, ep):
            mesh = make_mesh(devices[:8], dp=dp, sp=2, mp=2, ep=ep)
            tr = LMTrainer(model, mesh, optimizer=_sgd())
            state = tr.init_state(seed=3)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            state, loss = tr.train_step(state, x, y)
            return (jax.device_get(state.params),
                    float(np.mean(np.asarray(loss))))

        ref_p, ref_l = run(2, 1)
        got_p, got_l = run(1, 2)
        assert abs(got_l - ref_l) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)

    @pytest.mark.slow  # two 8-device pp x sp x ep compiles; pp x ep
    # and pp x sp are pinned fast
    def test_pp_sp_ep_matches_folded(self, devices):
        """pp x sp x ep (round-5): ring attention AND the expert
        all_to_all both ride inside the pipeline stages, orthogonal to
        the stage ring. Exact vs ep folded into dp on the same
        pp=2 x sp=2 mesh."""
        model = _moe()
        tokens = _tokens(b=8)

        def run(dp, ep):
            mesh = make_mesh(devices[:8], dp=dp, sp=2, mp=1, pp=2,
                             ep=ep)
            tr = PipelineLMTrainer(model, mesh, num_micro=2,
                                   optimizer=_sgd())
            state = tr.init_state(seed=3)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            state, loss = tr.train_step(state, x, y)
            return (jax.device_get(state.params),
                    float(np.mean(np.asarray(loss))))

        ref_p, ref_l = run(2, 1)
        got_p, got_l = run(1, 2)
        assert abs(got_l - ref_l) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)

    def test_ep_requires_moe_model(self, devices):
        dense = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=1, ep=2)
        with pytest.raises(ValueError, match="moe_experts"):
            LMTrainer(dense, mesh)

    def test_indivisible_experts_raises(self):
        with pytest.raises(ValueError, match="not"):
            _moe().with_expert_parallel(EXPERT_AXIS, 3)
