"""Mixture-of-experts + expert parallelism: the ep-sharded MoE computes
the same function as its single-device execution (drop-free capacity),
the router is differentiable, and MoE composes with dp and pp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.optim import SGD
from tpu_ddp.parallel.mesh import EXPERT_AXIS, make_mesh
from tpu_ddp.parallel.moe import switch_route
from tpu_ddp.train.lm import (LMTrainer, PipelineLMTrainer, make_lm_batch)


def _moe(**kw):
    cfg = dict(max_seq_len=32, compute_dtype=jnp.float32,
               moe_capacity_factor=8.0)  # drop-free for equivalence tests
    cfg.update(kw)
    return make_transformer("TransformerLM-moe-tiny", **cfg)


def _sgd():
    return SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)


def _tokens(b=4, L=33, seed=11):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 1024, size=(b, L))


def _one_moe_step(devices, dp, ep, tokens, **model_kw):
    """One SGD step of a MoE LM on a dp x ep mesh; returns (params,
    mean loss). Shared by the top-1 and top-2 equivalence tests."""
    model = _moe(**model_kw)
    mesh = make_mesh(devices[:dp * ep], dp=dp, sp=1, mp=1, pp=1, ep=ep)
    tr = LMTrainer(model, mesh, optimizer=_sgd())
    state = tr.init_state(seed=3)
    x, y = tr.put_batch(*make_lm_batch(tokens))
    state, loss = tr.train_step(state, x, y)
    return jax.device_get(state.params), float(np.mean(np.asarray(loss)))


class TestSwitchRouting:
    def test_dispatch_shapes_and_capacity(self):
        logits = jnp.asarray(np.random.default_rng(0).normal(
            size=(16, 4)).astype(np.float32))
        dispatch, combine, aux = switch_route(logits, 4, capacity=2)
        assert dispatch.shape == (16, 4, 2)
        # At most `capacity` tokens per expert slot column.
        assert float(jnp.max(jnp.sum(dispatch, axis=(0, 2)))) <= 2 + 1e-6
        # Each kept token occupies exactly one (expert, slot).
        per_token = jnp.sum(dispatch, axis=(1, 2))
        assert set(np.unique(np.asarray(per_token))) <= {0.0, 1.0}
        assert np.isfinite(float(aux))

    def test_balanced_routing_aux_is_one(self):
        # Perfectly uniform router -> f_e = P_e = 1/E -> aux = E*E*(1/E^2).
        logits = jnp.zeros((8, 4), jnp.float32)
        _, _, aux = switch_route(logits, 4, capacity=8)
        assert abs(float(aux) - 1.0) < 1e-5


class TestTopKRouting:
    def _route(self, T=16, E=4, C=32, k=2, seed=0):
        from tpu_ddp.parallel.moe import topk_route
        logits = jnp.asarray(np.random.default_rng(seed).normal(
            size=(T, E)).astype(np.float32))
        return topk_route(logits, E, C, top_k=k)

    def test_top2_two_assignments_per_token(self):
        dispatch, combine, aux = self._route()
        per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
        # Generous capacity: every token keeps both its choices.
        np.testing.assert_array_equal(per_token, 2.0)
        # Each (expert, slot) pair holds at most one token.
        per_slot = np.asarray(jnp.sum(dispatch, axis=0))
        assert per_slot.max() <= 1.0 + 1e-6
        assert np.isfinite(float(aux))

    def test_top2_gates_normalized(self):
        dispatch, combine, _ = self._route()
        # Kept tokens' combine weights sum to ~1 over their two slots.
        w = np.asarray(jnp.sum(combine, axis=(1, 2)))
        np.testing.assert_allclose(w, 1.0, rtol=1e-5)

    def test_top1_reduces_to_switch(self):
        from tpu_ddp.parallel.moe import switch_route, topk_route
        logits = jnp.asarray(np.random.default_rng(3).normal(
            size=(16, 4)).astype(np.float32))
        d1, c1, a1 = switch_route(logits, 4, 8)
        d2, c2, a2 = topk_route(logits, 4, 8, top_k=1)
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
        np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
        assert float(a1) == float(a2)

    @pytest.mark.slow  # two 4-device MoE train compiles; the k=1 ep
    # equivalence runs fast above (test_step_matches_unsharded[1-2])
    # and the k=2 routing math is pinned jit-vs-eager in this class.
    def test_top2_ep_sharded_step_matches_unsharded(self, devices):
        """The ep equivalence holds for k=2 routing too."""
        tokens = _tokens(seed=21)
        ref_p, ref_loss = _one_moe_step(devices, 4, 1, tokens,
                                        moe_top_k=2)
        got_p, got_loss = _one_moe_step(devices, 1, 4, tokens,
                                        moe_top_k=2)
        assert abs(got_loss - ref_loss) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)

    def test_top_k_validation(self):
        from tpu_ddp.parallel.moe import topk_route
        logits = jnp.zeros((8, 4), jnp.float32)
        with pytest.raises(ValueError, match="top_k"):
            topk_route(logits, 4, 8, top_k=0)
        with pytest.raises(ValueError, match="top_k"):
            topk_route(logits, 4, 8, top_k=5)

    def test_top2_tight_capacity_slots_never_collide(self):
        """Capacity overflow with k=2: a token's SECOND choice queues
        after the slots the first choices kept (the ``base`` offset in
        topk_route) — so even at tight capacity no (expert, slot) pair
        ever holds two tokens and no expert keeps more than C."""
        from tpu_ddp.parallel.moe import topk_route
        for seed in range(5):
            logits = jnp.asarray(np.random.default_rng(seed).normal(
                size=(16, 4)).astype(np.float32))
            dispatch, combine, _ = topk_route(logits, 4, 2, top_k=2)
            per_slot = np.asarray(jnp.sum(dispatch, axis=0))  # (E, C)
            assert per_slot.max() <= 1.0, seed
            per_expert = np.asarray(jnp.sum(dispatch, axis=(0, 2)))
            assert per_expert.max() <= 2.0, seed
        # Worst case: every token first-picks expert 0, second-picks
        # expert 1 — each expert keeps exactly its C earliest tokens.
        logits = jnp.tile(jnp.asarray([[3.0, 1.0]]), (8, 1))
        dispatch, _, _ = topk_route(logits, 2, 2, top_k=2)
        d = np.asarray(dispatch)
        assert np.asarray(jnp.sum(dispatch, axis=0)).max() == 1.0
        np.testing.assert_array_equal(d[0, 0], [1.0, 0.0])  # t0 -> e0s0
        np.testing.assert_array_equal(d[1, 0], [0.0, 1.0])  # t1 -> e0s1
        np.testing.assert_array_equal(d[0, 1], [1.0, 0.0])  # t0 -> e1s0
        assert d[2:].sum() == 0.0  # tokens 2..7: both choices dropped

    def test_aux_matches_hand_computed_example(self):
        """Pin the load-balance loss against the Switch formula worked
        by hand on 4 tokens / 2 experts: tokens 0, 1, 3 route to expert
        0, token 2 to expert 1, every row's softmax is (p, q) or (q, p)
        with p = e^2/(e^2+1). f = (3/4, 1/4), P = ((3p+q)/4, (p+3q)/4),
        aux = E * (f0*P0 + f1*P1)."""
        import math

        from tpu_ddp.parallel.moe import topk_route
        logits = jnp.asarray([[2.0, 0.0], [2.0, 0.0],
                              [0.0, 2.0], [2.0, 0.0]], jnp.float32)
        _, _, aux = topk_route(logits, 2, 8, top_k=1)
        p = math.exp(2.0) / (math.exp(2.0) + 1.0)
        q = 1.0 - p
        want = 2.0 * (0.75 * (3 * p + q) / 4 + 0.25 * (p + 3 * q) / 4)
        assert abs(float(aux) - want) < 1e-6

    def test_dropped_tokens_ride_residual_bitwise(self):
        """Overflowed assignments contribute EXACT zeros to the MoE
        MLP's output, so the transformer block's ``x + mlp(x)`` leaves
        a dropped token's residual stream bitwise unchanged — drops
        degrade quality, never numerics."""
        from tpu_ddp.parallel.moe import moe_mlp
        rng = np.random.default_rng(7)
        y = jnp.asarray(rng.normal(size=(1, 8, 4)).astype(np.float32))
        router_w = jnp.zeros((4, 2), jnp.float32)  # ties -> expert 0
        w1 = jnp.asarray(rng.normal(size=(2, 4, 8)).astype(np.float32))
        w2 = jnp.asarray(rng.normal(size=(2, 8, 4)).astype(np.float32))
        # T=8, cf=0.25, k=1, E=2 -> capacity 1: token 0 keeps the one
        # slot of expert 0, tokens 1..7 drop.
        out, _ = moe_mlp(y, router_w, w1, w2, num_experts=2,
                         capacity_factor=0.25)
        delta = np.asarray(out)[0]
        assert np.abs(delta[0]).max() > 0.0       # kept token computes
        np.testing.assert_array_equal(delta[1:], 0.0)
        x = np.asarray(y)[0]
        np.testing.assert_array_equal(x[1:] + delta[1:], x[1:])

    def test_routing_stats_counters(self):
        """The dropped-token fraction / load-histogram counters the
        train metrics line and bench's extra.moe probe carry
        (routing_stats): total collapse onto one expert at capacity 2
        keeps 2 of 8 assignments."""
        from tpu_ddp.parallel.moe import routing_stats, topk_route
        logits = jnp.tile(jnp.asarray([[3.0, 1.0]]), (8, 1))
        dispatch, _, _ = topk_route(logits, 2, 2, top_k=1)
        s = routing_stats(dispatch, top_k=1)
        assert abs(float(s["dropped_frac"]) - 0.75) < 1e-6
        np.testing.assert_allclose(np.asarray(s["expert_load"]),
                                   [0.25, 0.0], atol=1e-6)
        assert abs(float(s["imbalance"]) - 0.5) < 1e-6
        # Balanced drop-free routing: dropped 0, imbalance 1.
        logits = jnp.asarray(np.eye(4, dtype=np.float32).repeat(2, 0))
        dispatch, _, _ = topk_route(logits, 4, 8, top_k=1)
        s = routing_stats(dispatch, top_k=1)
        assert abs(float(s["dropped_frac"])) < 1e-6
        assert abs(float(s["imbalance"]) - 1.0) < 1e-6


class TestMoEForward:
    def test_apply_with_aux(self):
        model = _moe()
        params = model.init(jax.random.key(0))
        tokens = jnp.asarray(_tokens(2, 17)[:, :16])
        logits, aux = model.apply_with_aux(params, tokens)
        assert logits.shape == (2, 16, model.vocab_size)
        assert float(aux) > 0.0
        # Dense model reports zero aux.
        dense = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        dp = dense.init(jax.random.key(0))
        _, dense_aux = dense.apply_with_aux(dp, tokens)
        assert float(dense_aux) == 0.0

    def test_router_gradient_nonzero(self):
        model = _moe()
        params = model.init(jax.random.key(1))
        tokens = jnp.asarray(_tokens(2, 17)[:, :16])

        def loss(p):
            logits, aux = model.apply_with_aux(p, tokens)
            return jnp.mean(logits ** 2) + 0.01 * aux

        grads = jax.grad(loss)(params)
        g_router = np.asarray(grads["blocks"][0]["router"])
        assert np.abs(g_router).max() > 0.0


class TestExpertParallelEquivalence:
    @pytest.mark.parametrize("dp,ep", [
        # (1,4) only widens the expert axis (1,2) already pins.
        pytest.param(1, 4, marks=pytest.mark.slow),
        # dp x ep mixing is covered by (1,2)+(1,4) against the pure-ep
        # cells; (2,2) adds only one more mesh layout compile
        pytest.param(2, 2, marks=pytest.mark.slow),
        (1, 2)])
    def test_step_matches_unsharded(self, devices, dp, ep):
        tokens = _tokens()
        ref_p, ref_loss = _one_moe_step(devices, dp * ep, 1, tokens)
        got_p, got_loss = _one_moe_step(devices, dp, ep, tokens)
        assert abs(got_loss - ref_loss) < 1e-4, (dp, ep)
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=3e-4, atol=3e-5,
                err_msg=f"dp={dp} ep={ep}")

    def test_loss_decreases_with_drops(self, devices):
        """Tight capacity (tokens dropped) still trains stably."""
        model = _moe(moe_capacity_factor=0.5)
        mesh = make_mesh(devices[:4], dp=2, sp=1, mp=1, pp=1, ep=2)
        tr = LMTrainer(model, mesh)
        state = tr.init_state()
        x, y = tr.put_batch(*make_lm_batch(_tokens(b=4)))
        losses = []
        for _ in range(3):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestMoEComposition:
    def test_moe_under_pipeline(self, devices):
        """MoE blocks run under pp (experts stage-local, aux discarded)."""
        model = _moe()
        mesh = make_mesh(devices[:4], dp=2, sp=1, mp=1, pp=2)
        tr = PipelineLMTrainer(model, mesh, num_micro=2, optimizer=_sgd())
        state = tr.init_state(seed=0)
        x, y = tr.put_batch(*make_lm_batch(_tokens(b=4)))
        state, loss = tr.train_step(state, x, y)
        assert np.isfinite(float(np.mean(np.asarray(loss))))

    def _one_pp_step(self, devices, dp, ep, tokens, schedule="gpipe",
                     opt_sharding="replicated", steps=1):
        """One (or more) SGD steps of the MoE LM under pp=2 x dp x ep."""
        mesh = make_mesh(devices[:dp * 2 * ep], dp=dp, sp=1, mp=1, pp=2,
                         ep=ep)
        tr = PipelineLMTrainer(_moe(), mesh, num_micro=2,
                               optimizer=_sgd(), schedule=schedule,
                               opt_sharding=opt_sharding)
        state = tr.init_state(seed=3)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        loss = None
        for _ in range(steps):
            state, loss = tr.train_step(state, x, y)
        return (jax.device_get(state.params),
                float(np.mean(np.asarray(loss))))

    @pytest.mark.slow  # both schedules: two pp x ep compiles each on
    # the same cell; test_moe_under_pipeline above keeps the pp + ep
    # composition pinned in the fast tier.
    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_pp_ep_matches_stage_local(self, devices, schedule):
        """pp x ep (round-5): experts shard over ep WITHIN each stage
        (the MoE all_to_all rides inside the stage's blocks, orthogonal
        to the stage ring). Exact vs pp with stage-local full experts at
        the same total token sharding (dp x ep folded into dp) — the
        same equivalence contract the dense-trainer ep tests pin."""
        tokens = _tokens(b=8)
        ref_p, ref_l = self._one_pp_step(devices, 4, 1, tokens, schedule)
        got_p, got_l = self._one_pp_step(devices, 2, 2, tokens, schedule)
        assert abs(got_l - ref_l) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5,
                                       err_msg=schedule)

    @pytest.mark.slow  # two two-step 8-device pp x ep runs; the
    # one-step pp x ep exactness above stays in the default tier
    def test_pp_ep_zero1_matches_replicated_opt(self, devices):
        """pp x ep x ZeRO-1: stacked expert leaves' optimizer state lays
        out P((pp, ep, dp)) and the two-step update (momentum through
        the scattered layout) matches the replicated optimizer."""
        from jax.sharding import PartitionSpec as P
        from tpu_ddp.parallel.mesh import DATA_AXIS, PIPE_AXIS
        tokens = _tokens(b=8)
        ref_p, ref_l = self._one_pp_step(devices, 2, 2, tokens, steps=2)
        got_p, got_l = self._one_pp_step(devices, 2, 2, tokens, steps=2,
                                         opt_sharding="zero1")
        assert abs(got_l - ref_l) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)
        # Pin the three-axis state layout on the expert leaves.
        mesh = make_mesh(devices[:8], dp=2, sp=1, mp=1, pp=2, ep=2)
        tr = PipelineLMTrainer(_moe(), mesh, num_micro=2,
                               optimizer=_sgd(), opt_sharding="zero1")
        mom = tr.init_state(seed=0).opt_state["momentum"]
        w1 = mom["blocks"]["w1"]  # stacked (L, E, dm, dff), pp x ep
        assert w1.sharding.spec == P((PIPE_AXIS, EXPERT_AXIS, DATA_AXIS))
        assert w1.addressable_shards[0].data.size == w1.size // 8

    @pytest.mark.slow  # two 8-device MoE compiles; the pairwise cells
    # cover the semantics in the default tier
    def test_four_axis_matches_folded(self, devices):
        """The full dense-trainer matrix in ONE cell: sp x tp x ep
        (round-5 coverage pin — each pairwise composition was exact-
        tested, this pins the triple). Exact vs the same token sharding
        with ep folded into dp (the ep equivalence contract), both on
        sp=2 x mp=2."""
        model = _moe()
        tokens = _tokens(b=8)

        def run(dp, ep):
            mesh = make_mesh(devices[:8], dp=dp, sp=2, mp=2, ep=ep)
            tr = LMTrainer(model, mesh, optimizer=_sgd())
            state = tr.init_state(seed=3)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            state, loss = tr.train_step(state, x, y)
            return (jax.device_get(state.params),
                    float(np.mean(np.asarray(loss))))

        ref_p, ref_l = run(2, 1)
        got_p, got_l = run(1, 2)
        assert abs(got_l - ref_l) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)

    @pytest.mark.slow  # two 8-device pp x sp x ep compiles; pp x ep
    # and pp x sp are pinned fast
    def test_pp_sp_ep_matches_folded(self, devices):
        """pp x sp x ep (round-5): ring attention AND the expert
        all_to_all both ride inside the pipeline stages, orthogonal to
        the stage ring. Exact vs ep folded into dp on the same
        pp=2 x sp=2 mesh."""
        model = _moe()
        tokens = _tokens(b=8)

        def run(dp, ep):
            mesh = make_mesh(devices[:8], dp=dp, sp=2, mp=1, pp=2,
                             ep=ep)
            tr = PipelineLMTrainer(model, mesh, num_micro=2,
                                   optimizer=_sgd())
            state = tr.init_state(seed=3)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            state, loss = tr.train_step(state, x, y)
            return (jax.device_get(state.params),
                    float(np.mean(np.asarray(loss))))

        ref_p, ref_l = run(2, 1)
        got_p, got_l = run(1, 2)
        assert abs(got_l - ref_l) < 1e-4
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)

    def test_ep_requires_moe_model(self, devices):
        dense = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=1, ep=2)
        with pytest.raises(ValueError, match="moe_experts"):
            LMTrainer(dense, mesh)

    def test_indivisible_experts_raises(self):
        with pytest.raises(ValueError, match="not"):
            _moe().with_expert_parallel(EXPERT_AXIS, 3)


class TestZeroMoECompose:
    def test_zero1_layout_and_cross_layout_restore_bitwise(
            self, devices, tmp_path):
        """ZeRO-1 x ep (the §28 composition rule): non-expert leaves'
        optimizer state shards over dp while stacked expert leaves stay
        ep-owned (state P((ep, dp)) — dp WITHIN the expert cell, never
        across it), and a checkpoint written from that layout restores
        BITWISE into a replicated single-device trainer (the round-11
        cross-layout pattern: checkpoints hold canonical shapes)."""
        from jax.sharding import PartitionSpec as P
        from tpu_ddp.parallel.mesh import DATA_AXIS
        model = _moe()
        mesh = make_mesh(devices[:4], dp=2, sp=1, mp=1, pp=1, ep=2)
        tr = LMTrainer(model, mesh, optimizer=_sgd(),
                       opt_sharding="zero1")
        state = tr.init_state(seed=3)
        mom = state.opt_state["momentum"]
        assert mom["blocks"][0]["w1"].sharding.spec \
            == P((EXPERT_AXIS, DATA_AXIS))
        assert mom["embed"].sharding.spec == P(DATA_AXIS)
        x, y = tr.put_batch(*make_lm_batch(_tokens(b=4)))
        for _ in range(2):
            state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)

        tr2 = LMTrainer(model, make_mesh(devices[:1]),
                        optimizer=_sgd())
        st2 = tr2.restore_checkpoint(str(tmp_path))
        assert st2.step == 2
        want_p = tr.params_to_host(state)
        got_p = jax.device_get(st2.params)
        for a, b in zip(jax.tree.leaves(want_p), jax.tree.leaves(got_p)):
            np.testing.assert_array_equal(np.asarray(b), np.asarray(a))
        # The momentum too: canonicalized source vs restored replicated.
        canon = tr.optimizer.canonicalize_opt_host(
            tr._gather_to_host(state.opt_state))
        got_m = jax.device_get(st2.opt_state)
        for a, b in zip(jax.tree.leaves(canon), jax.tree.leaves(got_m)):
            np.testing.assert_array_equal(np.asarray(b), np.asarray(a))


class TestMoEDecode:
    """MoE serving (models/decode.py cached MoE-MLP path): expert
    capacity is computed from the LIVE bank size inside moe_mlp, so at
    generous capacity nothing drops and every token's MoE output is
    independent of batch composition — the greedy stream equals naive
    ``apply`` argmax decoding exactly."""

    def _model(self):
        # Generous capacity: drop-free at every live bank size, so the
        # parity claim below is exact (at tight capacity decode and
        # apply see DIFFERENT token mixes per routing problem and CAN
        # diverge — surfaced by the dropped-token counter, never
        # silent; models/decode.py:mlp).
        return _moe(max_seq_len=64)

    @pytest.mark.slow  # the per-token apply loop recompiles per
    # prompt length; test_engine_serves_moe_and_int8_refuses below
    # pins the same cached-MoE decode stream against generate fast.
    def test_greedy_stream_matches_apply(self):
        from tpu_ddp.models.generate import generate
        model = self._model()
        params = model.init(jax.random.key(0))
        prompt = _tokens(b=2, L=7, seed=5)
        got = np.asarray(generate(model, params, prompt, 5))

        for b in range(2):
            seq = list(prompt[b])
            for i in range(5):
                logits = np.asarray(model.apply(
                    params, jnp.asarray([seq], jnp.int32)))[0, -1]
                tok = int(np.argmax(logits))
                assert got[b, i] == tok, (b, i)
                seq.append(tok)

    def test_engine_serves_moe_and_int8_refuses(self):
        from tpu_ddp.models.generate import generate
        from tpu_ddp.serve.engine import ServeEngine
        model = self._model()
        params = model.init(jax.random.key(0))
        eng = ServeEngine(model, params, num_slots=4, block_size=8,
                          prefill_chunk=8)
        cases = [(7, 5), (11, 4)]
        reqs = [eng.submit(_tokens(b=1, L=L, seed=20 + i)[0], n)
                for i, (L, n) in enumerate(cases)]
        eng.run()
        for i, ((L, n), req) in enumerate(zip(cases, reqs)):
            want = np.asarray(generate(
                model, params, _tokens(b=1, L=L, seed=20 + i), n))[0]
            np.testing.assert_array_equal(np.asarray(req.tokens), want,
                                          err_msg=f"request {i}")
        # int8 decode quant refuses MoE loudly (the routed expert
        # einsums bypass ops/quant.qdot — serve/engine.py).
        with pytest.raises(ValueError, match="decode_quant"):
            ServeEngine(model, params, num_slots=4, block_size=8,
                        prefill_chunk=8, decode_quant="int8")
        # A training-sharded tree still refuses decode outright.
        with pytest.raises(ValueError, match="single-device"):
            generate(model.with_expert_parallel(EXPERT_AXIS, 2),
                     params, _tokens(b=1, L=4), 2)


class TestRouteStatsProbe:
    def test_trainer_route_stats_and_metrics_line(self, devices):
        """The training-metrics surface: LMTrainer.route_stats reports
        one counter dict per routed layer — loads summing to
        1 - dropped_frac — identically from an ep-sharded and a
        single-device trainer (it runs on canonical gathered params),
        and format_route_stats renders the metrics-line fragment.
        Dense models report [] and an empty fragment."""
        from tpu_ddp.train.lm import format_route_stats
        model = _moe()
        tokens = _tokens(b=4)[:, :-1]

        def probe(dp, ep):
            mesh = make_mesh(devices[:dp * ep], dp=dp, ep=ep)
            tr = LMTrainer(model, mesh, optimizer=_sgd())
            return tr, tr.route_stats(tr.init_state(seed=3), tokens)

        _, stats = probe(1, 1)
        assert len(stats) == model.num_layers
        for s in stats:
            load = np.asarray(s["expert_load"])
            assert load.shape == (model.moe_experts,)
            np.testing.assert_allclose(load.sum(),
                                       1.0 - float(s["dropped_frac"]),
                                       atol=1e-5)
            assert 0.0 <= float(s["dropped_frac"]) <= 1.0
        _, sharded = probe(2, 2)
        for a, b in zip(stats, sharded):
            np.testing.assert_allclose(np.asarray(b["expert_load"]),
                                       np.asarray(a["expert_load"]),
                                       atol=1e-6)
        line = format_route_stats(stats)
        assert line.startswith(" moe dropped=") and "imbalance=" in line
        assert line.count("/") == 2 * (model.num_layers - 1)

        dense = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        tr = LMTrainer(dense, make_mesh(devices[:1]))
        assert tr.route_stats(tr.init_state(), tokens) == []
        assert format_route_stats([]) == ""
