"""Chunked-vocab cross-entropy: identical values and gradients to the
dense logits path, without materializing (T, V)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.loss import (chunked_vocab_cross_entropy,
                              softmax_cross_entropy)
from tpu_ddp.ops.optim import SGD
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.train.lm import LMTrainer, make_lm_batch


class TestChunkedCE:
    def _case(self, T=32, dm=16, V=256, seed=0):
        rng = np.random.default_rng(seed)
        hidden = jnp.asarray(rng.normal(size=(T, dm)).astype(np.float32))
        head = jnp.asarray(rng.normal(size=(dm, V)).astype(np.float32))
        labels = jnp.asarray(rng.integers(0, V, size=T).astype(np.int32))
        return hidden, head, labels

    @pytest.mark.parametrize("chunk", [32, 64, 256])
    def test_values_match_dense(self, chunk):
        hidden, head, labels = self._case()
        got = chunked_vocab_cross_entropy(hidden, head, labels, chunk)
        want = softmax_cross_entropy(jnp.dot(hidden, head), labels)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_grads_match_dense(self):
        hidden, head, labels = self._case(seed=1)

        def chunked(h, w):
            return jnp.mean(chunked_vocab_cross_entropy(h, w, labels, 64))

        def dense(h, w):
            return jnp.mean(softmax_cross_entropy(jnp.dot(h, w), labels))

        gc = jax.grad(chunked, argnums=(0, 1))(hidden, head)
        gd = jax.grad(dense, argnums=(0, 1))(hidden, head)
        for a, b, name in zip(gc, gd, ("hidden", "head")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6,
                                       err_msg=name)

    def test_indivisible_chunk_raises(self):
        hidden, head, labels = self._case()
        with pytest.raises(ValueError, match="divisible"):
            chunked_vocab_cross_entropy(hidden, head, labels, 100)


class TestTrainerIntegration:
    def test_step_matches_dense_path(self, devices):
        """One LMTrainer step with vocab_chunk equals the default path."""
        tokens = np.random.default_rng(5).integers(0, 1024, size=(4, 33))
        results = []
        for chunk in (0, 128):
            model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                     compute_dtype=jnp.float32)
            tr = LMTrainer(model, make_mesh(devices[:2], dp=2),
                           optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                         weight_decay=1e-4),
                           vocab_chunk=chunk)
            state = tr.init_state(seed=3)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            state, loss = tr.train_step(state, x, y)
            results.append((jax.device_get(state.params),
                            float(np.mean(np.asarray(loss)))))
        (p0, l0), (p1, l1) = results
        assert abs(l0 - l1) < 1e-5
        for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-6)

    def test_validates_divisibility(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        with pytest.raises(ValueError, match="vocab_chunk"):
            LMTrainer(model, make_mesh(devices[:2], dp=2),
                      vocab_chunk=100)
