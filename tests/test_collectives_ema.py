"""Collectives microbenchmark (utils/collectives.py) and parameter EMA
(ops/ema.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.ops.ema import EMA
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.utils.collectives import bench_collectives


class TestCollectivesBench:
    def test_reports_all_ops(self, devices):
        mesh = make_mesh(devices[:4])
        out = bench_collectives(mesh, mb=0.5, iters=2)
        assert set(out) == {"psum", "psum_scatter", "all_gather",
                            "ppermute", "all_to_all"}
        for r in out.values():
            assert r["ms"] > 0 and r["gbps"] > 0

    def test_needs_two_devices(self, devices):
        with pytest.raises(ValueError, match="need >= 2"):
            bench_collectives(make_mesh(devices[:1]), mb=0.5)


class TestScheduledSGD:
    def test_schedule_drives_lr_and_resumes(self):
        from tpu_ddp.ops.optim import SGD, warmup_cosine

        opt = SGD(learning_rate=warmup_cosine(1.0, 2, 10),
                  momentum=0.0, weight_decay=0.0)
        p = {"w": jnp.asarray([0.0])}
        g = {"w": jnp.asarray([1.0])}
        s = opt.init(p)
        assert int(s["count"]) == 0
        p1, s = opt.apply(p, g, s)        # step 1: lr = 0.5 (warmup)
        np.testing.assert_allclose(np.asarray(p1["w"]), [-0.5], rtol=1e-6)
        p2, s = opt.apply(p1, g, s)       # step 2: lr = 1.0 (peak)
        np.testing.assert_allclose(np.asarray(p2["w"]), [-1.5], rtol=1e-6)
        assert int(s["count"]) == 2

    def test_plain_sgd_state_unchanged(self):
        from tpu_ddp.ops.optim import SGD
        s = SGD().init({"w": jnp.zeros((2,))})
        assert set(s) == {"momentum"}  # stateless-count reference form

    def test_pallas_plus_schedule_rejected_at_construction(self):
        from tpu_ddp.ops.optim import SGD, warmup_cosine
        with pytest.raises(ValueError, match="static lr"):
            SGD(learning_rate=warmup_cosine(1.0, 2, 10), use_pallas=True)

    def test_scheduled_lr_preserves_param_dtype(self):
        from tpu_ddp.ops.optim import SGD, warmup_cosine
        opt = SGD(learning_rate=warmup_cosine(1.0, 2, 10), momentum=0.0,
                  weight_decay=0.0)
        p = {"w": jnp.zeros((2,), jnp.bfloat16)}
        g = {"w": jnp.ones((2,), jnp.bfloat16)}
        new_p, _ = opt.apply(p, g, opt.init(p))
        assert new_p["w"].dtype == jnp.bfloat16  # traced lr must not promote


class TestEMA:
    def test_tracks_constant_params(self):
        ema = EMA(decay=0.9)
        p = {"w": jnp.full((4,), 3.0)}
        s = ema.init(p)
        for _ in range(50):
            s = ema.update(s, p)
        np.testing.assert_allclose(np.asarray(ema.params(s)["w"]), 3.0,
                                   rtol=1e-6)

    def test_warmup_tracks_young_model_fast(self):
        """First update with warmup: d = 2/11, so EMA moves most of the
        way to the new params instead of clinging to the init."""
        ema = EMA(decay=0.999, warmup=True)
        s = ema.init({"w": jnp.zeros(())})
        s = ema.update(s, {"w": jnp.ones(())})
        got = float(ema.params(s)["w"])
        want = 1.0 - 2.0 / 11.0
        np.testing.assert_allclose(got, want, rtol=1e-6)
        # Without warmup the same step barely moves.
        s2 = EMA(decay=0.999, warmup=False).init({"w": jnp.zeros(())})
        s2 = EMA(decay=0.999, warmup=False).update(s2, {"w": jnp.ones(())})
        assert float(s2["ema"]["w"]) < 0.01

    def test_fuses_into_jitted_step(self):
        ema = EMA(decay=0.99)

        @jax.jit
        def step(params, s):
            params = jax.tree.map(lambda p: p - 0.1, params)
            return params, ema.update(s, params)

        p = {"w": jnp.ones((8,))}
        s = ema.init(p)
        for _ in range(3):
            p, s = step(p, s)
        assert int(s["count"]) == 3
        assert np.isfinite(np.asarray(s["ema"]["w"])).all()
