"""The graph-audit sweep must pass on the live tree AND catch seeded
defects — the test_knob_audit.py doctrine applied to
scripts/graph_audit.py.

CI runs a reduced cell subset (two train rungs); the committed
experiments/graph_audit.json is the full sweep's zero-findings
baseline, and its integrity is asserted here so a finding-bearing
artifact can't be committed quietly.
"""

import json
import warnings
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from scripts.graph_audit import _program_audit, audit_train_cell, main

ARTIFACT = Path(__file__).parent.parent / "experiments" / \
    "graph_audit.json"


def test_train_cell_clean(devices):
    # The cheap live-tree gate: the fused rung (the round-3
    # workhorse), audited for donation, precision, and lowering
    # determinism. (The no-sync rung is covered by the main() subset
    # test below — no duplicate compiles in tier-1.)
    cell = audit_train_cell("fused")
    assert cell["findings"] == [], cell["findings"]
    assert cell["n_collectives"] >= 1
    assert cell["donated"], "train step donates its state"
    assert set(cell["donated"]) <= set(cell["aliased"])


def test_program_audit_reports_seeded_defect():
    # The sweep's own cell machinery must carry a defect through to
    # findings: a donated buffer no output can alias (dtype change).
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        f = jax.jit(lambda x: x.astype(jnp.int8), donate_argnums=0)
        cell = _program_audit(
            "seeded/defeated-donation",
            lambda: f.lower(jax.ShapeDtypeStruct((512,), jnp.float32)))
    assert any("copied every call" in s for s in cell["findings"])


def test_main_subset_exits_zero_without_writing(tmp_path, capsys):
    # The script surface the full sweep and CI share: a clean subset
    # returns 0 and prints the per-program lines; write=False leaves
    # the committed artifact alone.
    before = ARTIFACT.read_bytes()
    assert main(only=["train/none"], write=False) == 0
    assert ARTIFACT.read_bytes() == before
    out = capsys.readouterr().out
    assert "train/none" in out and "clean" in out


def test_committed_artifact_is_clean_and_complete():
    art = json.loads(ARTIFACT.read_text())
    assert art["n_findings"] == 0 and art["n_errors"] == 0
    programs = {c["program"] for c in art["cells"]}
    # Every engine family the repo ships is fingerprinted.
    for needle in ("train/none", "train/gather_scatter",
                   "train/all_reduce", "train/fused", "train/zero",
                   "train/fsdp", "train/fused+bf16", "train/fused+int8",
                   "train/fused+overlap", "mpmd/stage0-fwd",
                   "serve/decode", "serve/prefill",
                   "fleet/adopt-decode", "redistribute/src-dp4",
                   "redistribute/dst-dp2", "train/moe-dp2ep2",
                   "serve/moe-decode", "serve/moe-prefill"):
        assert needle in programs, needle
    # Fingerprints are recorded (the lockstep baseline a future run
    # can diff against), and the dp rungs actually collect.
    cells = {c["program"]: c for c in art["cells"]}
    assert cells["train/fused"]["n_collectives"] > 0
    # The MoE train step is the one program with the paired expert
    # all_to_alls — it must actually collect (deadlock class needs a
    # fingerprint to lockstep-check against).
    assert cells["train/moe-dp2ep2"]["n_collectives"] > 0
    assert all("fingerprint" in c for c in art["cells"])
