"""Test configuration: force a virtual 8-device CPU platform.

The reference was verified on a real 4-node cluster and has no test suite
(SURVEY.md §4); our strategy is the one §4/§7 prescribe: multi-device tests
on the forced host platform.

Note: this environment pre-imports jax at interpreter startup (site hook)
with the TPU platform selected, so setting ``JAX_PLATFORMS`` via os.environ
here is too late — we go through ``jax.config.update`` instead, which works
as long as no backend has been initialized yet.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
