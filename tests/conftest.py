"""Test configuration: force a virtual 8-device CPU platform.

The reference was verified on a real 4-node cluster and has no test suite
(SURVEY.md §4); our strategy is the one §4/§7 prescribe: multi-device tests
on the forced host platform.

Note: this environment pre-imports jax at interpreter startup (site hook)
with the TPU platform selected, so setting ``JAX_PLATFORMS`` via os.environ
here is too late — we go through ``jax.config.update`` instead, which works
as long as no backend has been initialized yet.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: OPT-IN via TPU_DDP_TEST_CACHE, off
# by default. It used to default to /tmp/tpu_ddp_jax_cache as a
# wall-clock lever (fresh trainer closures never hit the in-process jit
# cache, but the persistent cache keys on the HLO itself), but on this
# jaxlib (0.4.37, forced 8-device CPU host platform) DESERIALIZING a
# cached sharded-trainer executable corrupts the heap — reproduced as
# "corrupted double-linked list" / SIGSEGV aborting the whole pytest
# session at the first test whose step program is an exact HLO repeat
# of an earlier one (within a run or from a previous run's dir), while
# the identical sequence with the cache off passes. Compilation is
# stable; only cache LOADS crash. Set TPU_DDP_TEST_CACHE on a jaxlib
# where round-tripping works to get the old behavior.
_cache_dir = os.environ.get("TPU_DDP_TEST_CACHE")
if _cache_dir:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


_TRAINER_CACHE: dict = {}


def cached_vgg_trainer(devices, strategy, dp=4):
    """Session-cached VGG Trainer per (strategy, dp) — construction
    re-traces and reloads the compiled step from the persistent cache
    (~1-2 s each on the 1-core CI host). Trainers hold no per-run
    mutable state, so test modules share them and rebuild their own
    TrainStates. Per-process, so safe under `pytest -n auto`."""
    key = (strategy, dp)
    if key not in _TRAINER_CACHE:
        import numpy as np

        from tpu_ddp.models import get_model
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.engine import Trainer
        from tpu_ddp.utils.config import TrainConfig

        mesh = make_mesh(devices[:dp])
        model = get_model("VGG11", compute_dtype=np.float32)
        _TRAINER_CACHE[key] = Trainer(model, TrainConfig(),
                                      strategy=strategy, mesh=mesh)
    return _TRAINER_CACHE[key]


@pytest.fixture
def no_retrace():
    """The retrace sentinel (tpu_ddp/analysis/retrace.py) as a fixture:

        def test_loop(no_retrace):
            with no_retrace(watch=("train_step",)):
                for _ in range(5):
                    trainer.train_step(state, *batch)

    Raises RetraceError on exit if any watched callable compiled more
    than once (the round-8 bug class: a "compiled" loop re-lowering
    every call)."""
    from tpu_ddp.analysis.retrace import no_retrace as _nr
    return _nr
