"""Overlapped bucketized gradient collectives + sharded weight update
(tpu_ddp/parallel/overlap.py) — correctness of the perf path.

The overlap path re-plumbs HOW gradients move (size-targeted buckets
issued mid-backward, optionally scatter + sharded optimizer + param
all-gather) without changing WHAT the step computes, so the tests here
are equivalence claims against the committed rungs:

- bucket partition/combine is a lossless permutation in reverse
  flatten (≈ reverse autodiff) order;
- per-rung gradients and 3-step trajectories match the unbucketed
  sync.py rung within the fp32 reduction-order tolerance of
  tests/test_sync.py (rtol=1e-5/atol=1e-6);
- the 2004.13336-style sharded update is BITWISE the replicated SGD
  update when fed identical pre-synced gradients (both sides jitted:
  jit-vs-eager FMA fusion alone breaks bit-equality);
- the compiled step's collectives are dataflow-overlappable per
  hlo_comm.assert_overlap, and the single-bucket control is NOT —
  the verdict distinguishes structure, not scheduler luck;
- StepGuard skips stay exact no-ops (incl. the int8 pre-cast
  nonfinite flag, since a NaN cast to int8 would otherwise vanish),
  K-step scan and dispatch_depth keep bit-identical numerics, and
  checkpoints round-trip across sharded/replicated layouts.
"""

import dataclasses
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from tpu_ddp.models.vgg import VGGModel
from tpu_ddp.ops.optim import SGD, clip_scale_from_sq, clip_tree
from tpu_ddp.parallel.compress import get_compressor
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.parallel.overlap import (BucketPlan, OverlapSync,
                                      ShardedUpdate)
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils import hlo_comm
from tpu_ddp.utils.config import TrainConfig

DISTRIBUTED = ["gather_scatter", "all_reduce", "fused"]
AX = "dp"


@dataclasses.dataclass(frozen=True)
class TinyNoBN:
    """Per-example-decoupled conv+dense model (test_sync.py's): BN-free
    so distributed == single-device holds exactly and tolerances stay
    the reduction-order ones."""

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {
            "conv": 0.3 * jax.random.normal(k1, (3, 3, 3, 8)),
            "bias": jnp.zeros((8,)),
            "head": 0.3 * jax.random.normal(k2, (2 * 2 * 8, 10)),
            "head_b": 0.01 * jax.random.normal(k3, (10,)),
        }

    def apply(self, params, x):
        y = lax.conv_general_dilated(
            x, params["conv"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = jnp.maximum(y + params["bias"], 0)
        y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 2, 2, 1),
                              (1, 2, 2, 1), "VALID")
        return y.reshape(y.shape[0], -1) @ params["head"] + params["head_b"]


@dataclasses.dataclass(frozen=True)
class WideMLP:
    """~2.2 MiB of params across 4 dense layers: several buckets at
    bucket_mb=1, and `dot` heavy ops for the HLO dataflow tests."""

    def init(self, key):
        ks = jax.random.split(key, 4)
        return {
            "w1": 0.05 * jax.random.normal(ks[0], (48, 256)),
            "w2": 0.05 * jax.random.normal(ks[1], (256, 1024)),
            "w3": 0.05 * jax.random.normal(ks[2], (1024, 512)),
            "w4": 0.05 * jax.random.normal(ks[3], (512, 10)),
        }

    def apply(self, params, x):
        y = x.reshape(x.shape[0], -1)
        y = jnp.maximum(y @ params["w1"], 0)
        y = jnp.maximum(y @ params["w2"], 0)
        y = jnp.maximum(y @ params["w3"], 0)
        return y @ params["w4"]


def tiny_vgg():
    return VGGModel(name="tiny", cfg=(8, "M", 16, "M"),
                    compute_dtype=jnp.float32)


def batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4, 4, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


def run_steps(trainer, n_steps=3):
    state = trainer.init_state()
    losses = []
    for i in range(n_steps):
        x, y = batch(seed=i)
        xb, yb, wb = trainer.put_batch(x, y)
        state, loss = trainer.train_step(state, xb, yb, wb)
        losses.append(np.ravel(np.asarray(loss)))
    return state, losses


def params_allclose(a, b, **kw):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), **kw)


# --------------------------------------------------------------- plan

def _mlp_like_tree(key):
    return {
        "l1": {"w": jax.random.normal(key, (8, 16)),
               "b": jnp.zeros((16,))},
        "l2": {"w": jax.random.normal(jax.random.fold_in(key, 1),
                                      (16, 4)),
               "b": jnp.zeros((4,))},
    }


class TestBucketPlan:
    def test_round_trip_and_reverse_order(self):
        tree = _mlp_like_tree(jax.random.key(0))
        # 64 floats per bucket: forces several buckets on a tiny tree.
        plan = BucketPlan(jax.eval_shape(lambda: tree),
                          bucket_mb=64 * 4 / (1 << 20))
        assert plan.n_buckets >= 2
        part = plan.partition(tree)
        assert jax.tree.all(
            jax.tree.map(jnp.array_equal, plan.combine(part), tree))
        # Every leaf appears exactly once...
        seen = sorted(i for b in plan.buckets for i in b)
        assert seen == list(range(len(plan.metas)))
        # ...and bucket 0 starts at the LAST flatten index: buckets fill
        # in reverse autodiff order so output-side grads fire first.
        assert plan.buckets[0][0] == len(plan.metas) - 1
        # Size targeting: every multi-leaf bucket respects the byte cap.
        cap = 64 * 4
        for k, idxs in enumerate(plan.buckets):
            if len(idxs) > 1:
                assert plan.bucket_sizes()[k] * 4 <= cap

    def test_validation(self):
        tree = jax.eval_shape(lambda: {"w": jnp.zeros((4,))})
        with pytest.raises(ValueError):
            BucketPlan(tree, bucket_mb=0)
        with pytest.raises(ValueError):
            BucketPlan(jax.eval_shape(lambda: {}), bucket_mb=1)


# ------------------------------------------------- module-level sync

def _loss_terms(p, xb, yb):
    h = jnp.tanh(xb @ p["l1"]["w"] + p["l1"]["b"])
    out = h @ p["l2"]["w"] + p["l2"]["b"]
    l = jnp.mean((out - yb) ** 2)
    # engine convention: the rung's sync divides by world size itself
    return l, l


def _sync_fixture(n_dev, devices):
    mesh = Mesh(np.array(devices[:n_dev]), (AX,))
    key = jax.random.key(0)
    params = _mlp_like_tree(key)
    x = jax.random.normal(jax.random.fold_in(key, 2), (n_dev * 2, 8))
    y = jax.random.normal(jax.random.fold_in(key, 3), (n_dev * 2, 4))
    plan = BucketPlan(jax.eval_shape(lambda: params),
                      bucket_mb=64 * 4 / (1 << 20))

    def baseline(xb, yb):
        g = jax.grad(lambda p: _loss_terms(p, xb, yb)[0])(params)
        return jax.tree.map(lambda t: lax.psum(t, AX) / n_dev, g)

    base = jax.jit(jax.shard_map(
        baseline, mesh=mesh, in_specs=(P(AX), P(AX)), out_specs=P(),
        check_vma=False))(x, y)
    return mesh, params, x, y, plan, base


@pytest.mark.parametrize("kind", DISTRIBUTED)
def test_bucket_sync_matches_psum_baseline(kind, devices):
    n = 4
    mesh, params, x, y, plan, base = _sync_fixture(n, devices)
    ov = OverlapSync(plan, kind, AX, n)

    def body(xb, yb):
        _, grads, new_comp, extra = ov.value_and_grad(
            lambda p: _loss_terms(p, xb, yb), params)
        assert new_comp is None and extra is None
        if ov.scatter:
            # scatter kinds return the shard embedded at this replica's
            # offset (zeros elsewhere); psum reassembles the full mean.
            grads = jax.tree.map(lambda t: lax.psum(t, AX), grads)
        return grads

    g = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P(AX), P(AX)), out_specs=P(),
        check_vma=False))(x, y)
    params_allclose(g, base, rtol=1e-5, atol=1e-7)


def test_wire_formats_compose(devices):
    n = 4
    mesh, params, x, y, plan, base = _sync_fixture(n, devices)
    norm = np.linalg.norm(np.concatenate(
        [np.asarray(t).ravel() for t in jax.tree.leaves(base)]))

    def rel_err(g):
        d = np.linalg.norm(np.concatenate(
            [np.asarray(a).ravel() for a in jax.tree.leaves(g)]) -
            np.concatenate(
                [np.asarray(b).ravel() for b in jax.tree.leaves(base)]))
        return d / norm

    # int8 + error feedback on a scatter rung: quantized but close, the
    # EF residual populates, the shared seed advances once per step.
    comp8 = get_compressor("int8")
    cs = comp8.init_state(jax.eval_shape(lambda: params), dp=n, seed=0)
    ov8 = OverlapSync(plan, "all_reduce", AX, n, compressor=comp8)

    def body8(xb, yb, cs):
        _, grads, new_comp, extra = ov8.value_and_grad(
            lambda p: _loss_terms(p, xb, yb), params, cs)
        full = jax.tree.map(lambda t: lax.psum(t, AX), grads)
        return full, new_comp, extra

    specs = comp8.state_specs(cs)
    g8, nc, extra = jax.jit(jax.shard_map(
        body8, mesh=mesh, in_specs=(P(AX), P(AX), specs),
        out_specs=(P(), specs, P()), check_vma=False))(x, y, cs)
    assert float(np.asarray(extra)) == 0.0
    assert int(np.asarray(nc["seed"])) == 1
    assert any(np.any(np.asarray(r))
               for r in jax.tree.leaves(nc["residual"]))
    assert rel_err(g8) < 0.05

    # bf16 on the gather rung: half-precision wire, tiny error.
    ovb = OverlapSync(plan, "gather_scatter", AX, n,
                      compressor=get_compressor("bf16"))

    def bodyb(xb, yb):
        _, grads, nc2, e2 = ovb.value_and_grad(
            lambda p: _loss_terms(p, xb, yb), params)
        assert nc2 is None and e2 is None
        return grads

    gb = jax.jit(jax.shard_map(
        bodyb, mesh=mesh, in_specs=(P(AX), P(AX)), out_specs=P(),
        check_vma=False))(x, y)
    assert rel_err(gb) < 0.01


# ------------------------------------------------- sharded update

def test_sharded_update_matches_replicated_dp2(devices):
    """arxiv 2004.13336 §3: each replica updates its 1/N gradient shard
    and all-gathers fresh params. On dp=2: bitwise-identical state to
    the replicated SGD update on identical pre-synced gradients (both
    sides jitted), trajectory-equal end to end (reduction order differs:
    psum_scatter vs psum), and host canonicalization round-trips."""
    n = 2
    mesh, params, x, y, plan, base = _sync_fixture(n, devices)
    sgd = SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)
    shupd = ShardedUpdate(sgd, plan, AX, n)
    ov = OverlapSync(plan, "all_reduce", AX, n)
    pay_specs = shupd.state_specs()
    rep_specs = sgd.state_specs(P())

    # --- end-to-end 3-step trajectory (with clipping) ---------------
    def step_sharded(p, opt, xb, yb):
        _, grads, _, _ = ov.value_and_grad(
            lambda pp: _loss_terms(pp, xb, yb), p)
        return shupd.apply_scattered(p, grads, opt, clip_norm=1.0)

    def step_repl(p, opt, xb, yb):
        g = jax.grad(lambda pp: _loss_terms(pp, xb, yb)[0])(p)
        g = jax.tree.map(lambda t: lax.psum(t, AX) / n, g)
        sq = sum(jnp.sum(jnp.square(t)) for t in jax.tree.leaves(g))
        g = clip_tree(g, clip_scale_from_sq(sq, 1.0))
        return sgd.apply(p, g, opt)

    js = jax.jit(jax.shard_map(
        step_sharded, mesh=mesh, in_specs=(P(), pay_specs, P(AX), P(AX)),
        out_specs=(P(), pay_specs), check_vma=False))
    jr = jax.jit(jax.shard_map(
        step_repl, mesh=mesh, in_specs=(P(), rep_specs, P(AX), P(AX)),
        out_specs=(P(), rep_specs), check_vma=False))
    ps, opt_s = params, shupd.init(params)
    pr, opt_r = params, sgd.init(params)
    for _ in range(3):
        ps, opt_s = js(ps, opt_s, x, y)
        pr, opt_r = jr(pr, opt_r, x, y)
    params_allclose(ps, pr, rtol=1e-6, atol=1e-8)
    canon = shupd.canonicalize_opt_host(jax.tree.map(np.asarray, opt_s))
    params_allclose(canon["momentum"], opt_r["momentum"],
                    rtol=1e-6, atol=1e-8)
    # host converters are exact inverses
    back = shupd.flatten_opt(canon)
    for k in back["momentum"]:
        np.testing.assert_array_equal(
            back["momentum"][k], np.asarray(opt_s["momentum"][k]))

    # --- bitwise on identical pre-synced grads, no clip -------------
    def upd_sharded(p, opt, g):
        # re-embed the replica's shard of the full mean — the layout
        # OverlapSync's scatter kinds hand to apply_scattered
        idx = lax.axis_index(AX)
        g_leaves = jax.tree.leaves(g)
        emb = list(g_leaves)
        for k, idxs in enumerate(plan.buckets):
            chunk = shupd._chunks[k]
            flat = jnp.concatenate(
                [g_leaves[i].reshape(-1) for i in idxs])
            flat = jnp.pad(flat, (0, n * chunk - flat.shape[0]))
            sh = lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)
            fullz = lax.dynamic_update_slice(
                jnp.zeros((n * chunk,), jnp.float32), sh, (idx * chunk,))
            off = 0
            for i in idxs:
                m = plan.metas[i]
                emb[i] = fullz[off:off + m.size].reshape(m.shape)
                off += m.size
        ge = jax.tree.unflatten(jax.tree.structure(g), emb)
        return shupd.apply_scattered(p, ge, opt)

    p2, o2 = jax.jit(jax.shard_map(
        upd_sharded, mesh=mesh, in_specs=(P(), pay_specs, P()),
        out_specs=(P(), pay_specs), check_vma=False))(
            params, shupd.init(params), base)
    p2r, o2r = jax.jit(jax.shard_map(
        lambda p, o, g: sgd.apply(p, g, o), mesh=mesh,
        in_specs=(P(), rep_specs, P()),
        out_specs=(P(), rep_specs), check_vma=False))(
            params, sgd.init(params), base)
    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(p2r)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    canon2 = shupd.canonicalize_opt_host(jax.tree.map(np.asarray, o2))
    for a, b in zip(jax.tree.leaves(canon2["momentum"]),
                    jax.tree.leaves(o2r["momentum"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------- engine integration

@pytest.mark.parametrize("strategy", DISTRIBUTED)
def test_engine_trajectory_matches_unbucketed(strategy, devices):
    mesh = make_mesh(devices[:4])
    model = TinyNoBN()
    base = Trainer(model, TrainConfig(), strategy=strategy, mesh=mesh)
    sb, lb = run_steps(base)
    ov = Trainer(model, TrainConfig(overlap=True, bucket_mb=1),
                 strategy=strategy, mesh=mesh)
    assert ov._overlap_active
    assert (ov._sharded_update is not None) == (
        strategy in ("all_reduce", "fused"))
    so, lo = run_steps(ov)
    params_allclose(sb.params, so.params, rtol=1e-5, atol=1e-6)
    for a, b in zip(lb, lo):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_engine_trajectory_vgg_bn(devices):
    """Same claim through the real VGG builder (BN batch-stats path)."""
    mesh = make_mesh(devices[:4])
    model = tiny_vgg()
    sb, _ = run_steps(Trainer(model, TrainConfig(), strategy="fused",
                              mesh=mesh))
    so, _ = run_steps(Trainer(model,
                              TrainConfig(overlap=True, bucket_mb=1),
                              strategy="fused", mesh=mesh))
    params_allclose(sb.params, so.params, rtol=1e-5, atol=1e-6)


def test_engine_multibucket_trajectory(devices):
    """WideMLP at bucket_mb=1 actually splits into several buckets (the
    tiny models above fit one) and still matches unbucketed."""
    mesh = make_mesh(devices[:4])
    model = WideMLP()
    ov = Trainer(model, TrainConfig(overlap=True, bucket_mb=1),
                 strategy="all_reduce", mesh=mesh)
    assert ov._overlap.plan.n_buckets >= 2
    sb, _ = run_steps(Trainer(model, TrainConfig(),
                              strategy="all_reduce", mesh=mesh))
    so, _ = run_steps(ov)
    params_allclose(sb.params, so.params, rtol=1e-5, atol=1e-6)


def _step_hlo(trainer):
    state = trainer.init_state()
    staged = trainer.put_batch(*batch())
    return hlo_comm.train_step_hlo(trainer, state, *staged)


def test_assert_overlap_verdicts(devices):
    """The compiled bucketized step passes assert_overlap; the single-
    bucket control (one concatenated collective whose ancestor cone
    holds every dot) fails it — the dataflow predicate distinguishes
    bucketing structure, not scheduler behavior."""
    mesh = make_mesh(devices[:4])
    model = WideMLP()
    bucketed = Trainer(model, TrainConfig(overlap=True, bucket_mb=1),
                       strategy="fused", mesh=mesh)
    report = hlo_comm.assert_overlap(_step_hlo(bucketed))
    assert report["n_grad_collectives"] >= 2
    assert report["n_overlappable"] >= report["n_grad_collectives"] // 2
    assert report["n_heavy_ops"] > 0

    single = Trainer(model, TrainConfig(overlap=True, bucket_mb=1024),
                     strategy="fused", mesh=mesh)
    assert single._overlap.plan.n_buckets == 1
    hlo = _step_hlo(single)
    assert not hlo_comm.overlap_report(hlo)["overlapped"]
    with pytest.raises(AssertionError, match="not overlappable"):
        hlo_comm.assert_overlap(hlo)


def _nan_skip_is_noop(trainer):
    state = trainer.init_state()
    x, y = batch(seed=0)
    xb, yb, wb = trainer.put_batch(x, y)
    state, _ = trainer.train_step(state, xb, yb, wb)
    before = trainer.state_to_host(state)
    xn = x.copy()
    xn[0, 0, 0, 0] = np.nan
    xb2, yb2, wb2 = trainer.put_batch(xn, y)
    state2, fused = trainer.train_step_async(state, xb2, yb2, wb2)
    _, skipped = trainer._materialize_fused(fused)
    assert skipped
    after = trainer.state_to_host(state2)
    params_allclose(before["params"], after["params"], rtol=0, atol=0)
    params_allclose(before["opt_state"]["momentum"],
                    after["opt_state"]["momentum"], rtol=0, atol=0)
    return before, after


def test_guard_nan_skip_noop_sharded(devices):
    mesh = make_mesh(devices[:4])
    _nan_skip_is_noop(
        Trainer(TinyNoBN(), TrainConfig(overlap=True, bucket_mb=1),
                strategy="all_reduce", mesh=mesh))


def test_guard_nan_skip_int8_flag(devices):
    """Under int8 the wire would CAST the NaN away; the pre-cast
    nonfinite flag (OverlapSync's aux channel -> guard extra_bad) must
    still force the skip, and the rollback must also freeze the
    compressor state (seed + residuals)."""
    mesh = make_mesh(devices[:4])
    trainer = Trainer(
        TinyNoBN(), TrainConfig(overlap=True, bucket_mb=1,
                                grad_compress="int8"),
        strategy="all_reduce", mesh=mesh)
    before, after = _nan_skip_is_noop(trainer)
    params_allclose(before["comp_state"], after["comp_state"],
                    rtol=0, atol=0)


def test_int8_ef_composition_engine(devices):
    """int8 EF under overlap trains: finite losses, the shared seed
    advances once per step, the comp-state LAYOUT equals the unbucketed
    template (checkpoints/rollback unchanged), and params stay near the
    unbucketed int8 trajectory (different bucket shapes quantize
    differently — loose tolerance is expected)."""
    mesh = make_mesh(devices[:4])
    model = TinyNoBN()
    t8b = Trainer(model, TrainConfig(grad_compress="int8"),
                  strategy="all_reduce", mesh=mesh)
    s8b, _ = run_steps(t8b)
    t8 = Trainer(model, TrainConfig(overlap=True, bucket_mb=1,
                                    grad_compress="int8"),
                 strategy="all_reduce", mesh=mesh)
    assert t8._overlap_active and t8._comp_stateful
    seed0 = int(np.asarray(t8.init_state().comp_state["seed"]))
    s8, l8 = run_steps(t8)
    assert int(np.asarray(s8.comp_state["seed"])) == seed0 + 3
    assert jax.tree.structure(s8.comp_state) == jax.tree.structure(
        s8b.comp_state)
    assert all(np.all(np.isfinite(v)) for v in map(np.asarray, l8))
    params_allclose(s8b.params, s8.params, rtol=0.15, atol=0.02)


def test_kstep_scan_bit_equal(devices):
    mesh = make_mesh(devices[:4])
    model = TinyNoBN()
    tk = Trainer(model, TrainConfig(overlap=True, bucket_mb=1,
                                    steps_per_dispatch=2),
                 strategy="fused", mesh=mesh)
    multi = tk.build_multi_step(2)
    x0, y0 = batch(seed=0)
    x1, y1 = batch(seed=1)
    stk, _ = multi(tk.init_state(), np.stack([x0, x1]),
                   np.stack([y0, y1]))
    ref = Trainer(model, TrainConfig(overlap=True, bucket_mb=1),
                  strategy="fused", mesh=mesh)
    stref = ref.init_state()
    for i in range(2):
        xb, yb, wb = ref.put_batch(*batch(seed=i))
        stref, _ = ref.train_step(stref, xb, yb, wb)
    params_allclose(tk.state_to_host(stk)["params"],
                    ref.state_to_host(stref)["params"], rtol=0, atol=0)


def test_dispatch_depth_overlap(devices):
    """dispatch_depth pipelines host dispatch, never numerics: depth 3
    and depth 0 produce bit-identical params under overlap."""
    mesh = make_mesh(devices[:4])
    model = TinyNoBN()
    deep, _ = run_steps(Trainer(
        model, TrainConfig(overlap=True, bucket_mb=1, dispatch_depth=3),
        strategy="fused", mesh=mesh))
    sync, _ = run_steps(Trainer(
        model, TrainConfig(overlap=True, bucket_mb=1, dispatch_depth=0),
        strategy="fused", mesh=mesh))
    params_allclose(deep.params, sync.params, rtol=0, atol=0)


def test_checkpoint_round_trip_across_layouts(devices, tmp_path):
    """Sharded-update payload state checkpoints in canonical (momentum-
    as-param-tree) form: restore into a replicated trainer and back
    into a differently-rung overlapped one, bitwise both ways."""
    mesh = make_mesh(devices[:4])
    model = TinyNoBN()
    tov = Trainer(model, TrainConfig(overlap=True, bucket_mb=1),
                  strategy="fused", mesh=mesh)
    st, _ = run_steps(tov)
    tov.save_checkpoint(str(tmp_path), st)
    host_a = tov.state_to_host(st)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # informational layout notes
        trep = Trainer(model, TrainConfig(), strategy="fused", mesh=mesh)
        host_b = trep.state_to_host(trep.restore_checkpoint(str(tmp_path)))
        tov2 = Trainer(model, TrainConfig(overlap=True, bucket_mb=1),
                       strategy="all_reduce", mesh=mesh)
        host_c = tov2.state_to_host(tov2.restore_checkpoint(str(tmp_path)))
    for other in (host_b, host_c):
        params_allclose(host_a["params"], other["params"], rtol=0, atol=0)
        params_allclose(host_a["opt_state"]["momentum"],
                        other["opt_state"]["momentum"], rtol=0, atol=0)


def test_degrade_warnings(devices):
    mesh = make_mesh(devices[:4])
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        t = Trainer(TinyNoBN(), TrainConfig(overlap=True),
                    strategy="none", mesh=mesh)
    assert not t._overlap_active
    assert any("overlap" in str(x.message) for x in w)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        t = Trainer(TinyNoBN(), TrainConfig(overlap=True),
                    strategy="fused", mesh=None)
    assert not t._overlap_active
    assert any("overlap" in str(x.message) for x in w)


# --------------------------------------------------------- knob surfaces

def test_space_constraints():
    from tpu_ddp.tune.space import Workload, violations
    cpu1 = Workload(platform="cpu", dp=1, strategy="none")
    assert violations({"overlap": True}, cpu1)
    ok = Workload(platform="tpu", dp=8, strategy="fused")
    assert violations({"overlap": True}, ok) == []
    assert violations({"bucket_mb": 4}, ok)  # unread without overlap
    assert violations({"overlap": True, "bucket_mb": 4}, ok) == []


def test_config_env_knobs(monkeypatch):
    monkeypatch.setenv("TPU_DDP_OVERLAP", "1")
    monkeypatch.setenv("TPU_DDP_BUCKET_MB", "7")
    cfg = TrainConfig()
    assert cfg.overlap is True and cfg.bucket_mb == 7
    monkeypatch.setenv("TPU_DDP_BUCKET_MB", "0")
    with pytest.raises(ValueError, match="TPU_DDP_BUCKET_MB"):
        TrainConfig()
