"""DistributedShardSampler parity against torch.utils.data.DistributedSampler
(the reference's sharding mechanism, part2/part2b/main.py:78-79)."""

import numpy as np
import pytest
import torch
from torch.utils.data import DistributedSampler

from tpu_ddp.data.sampler import DistributedShardSampler


class _FakeDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n


@pytest.mark.parametrize("n,ws", [(50_000, 4), (10, 3), (7, 4), (16, 2),
                                  (5, 5), (1, 2)])
def test_no_shuffle_bit_parity_with_torch(n, ws):
    for rank in range(ws):
        torch_s = DistributedSampler(_FakeDataset(n), num_replicas=ws,
                                     rank=rank, shuffle=False,
                                     drop_last=False)
        ours = DistributedShardSampler(n, num_replicas=ws, rank=rank,
                                       shuffle=False, drop_last=False)
        np.testing.assert_array_equal(np.fromiter(iter(torch_s), dtype=np.int64),
                                      ours.indices())
        assert len(torch_s) == len(ours)


@pytest.mark.parametrize("n,ws", [(103, 4), (64, 8)])
def test_drop_last_parity_with_torch(n, ws):
    for rank in range(ws):
        torch_s = DistributedSampler(_FakeDataset(n), num_replicas=ws,
                                     rank=rank, shuffle=False, drop_last=True)
        ours = DistributedShardSampler(n, num_replicas=ws, rank=rank,
                                       shuffle=False, drop_last=True)
        np.testing.assert_array_equal(np.fromiter(iter(torch_s), dtype=np.int64),
                                      ours.indices())


def test_shuffle_is_a_partition_and_epoch_dependent():
    n, ws = 101, 4
    shards0, shards1 = [], []
    for rank in range(ws):
        s = DistributedShardSampler(n, num_replicas=ws, rank=rank,
                                    shuffle=True, seed=7)
        s.set_epoch(0)
        shards0.append(s.indices())
        s.set_epoch(1)
        shards1.append(s.indices())
    # Union of shards covers the dataset (with wrap padding allowed).
    assert set(np.concatenate(shards0)) == set(range(n))
    # set_epoch changes the permutation.
    assert any(not np.array_equal(a, b) for a, b in zip(shards0, shards1))
    # Same epoch is deterministic.
    s = DistributedShardSampler(n, num_replicas=ws, rank=2, shuffle=True,
                                seed=7)
    s.set_epoch(0)
    np.testing.assert_array_equal(s.indices(), shards0[2])
