"""Autotuner subsystem (tpu_ddp/tune/): space, cache, search, resolve.

Fast by construction: search logic runs against fake evaluate functions
(no compiles), the cache lifecycle against a tmp dir, and the constraint
model against synthetic Workload contexts. The one real measured-trial
search (the acceptance smoke: >=3 knobs, <120 s, cache hit on rerun)
is ``slow``-marked.
"""

import dataclasses
import json
import os
import time

import pytest

import tpu_ddp.tune as tune
from tpu_ddp.tune import cache as tcache
from tpu_ddp.tune.space import (KNOBS, Workload, fingerprint_for,
                                parse_knob_filter, searchable_knobs,
                                space_version, violations, workload_for)
from tpu_ddp.tune.search import run_search
from tpu_ddp.utils.config import TrainConfig
from tpu_ddp.utils.timing import timed_window_s, warm_then_median_s


CPU1 = Workload(platform="cpu", dp=1, processes=1, strategy="fused",
                collective_cadence=False)


@pytest.fixture()
def cfg(monkeypatch):
    for key in list(os.environ):
        if key.startswith("TPU_DDP_"):
            monkeypatch.delenv(key)
    return TrainConfig()


@pytest.fixture()
def cache_dir(tmp_path, monkeypatch):
    d = tmp_path / "tune"
    monkeypatch.setenv("TPU_DDP_TUNE_CACHE_DIR", str(d))
    return d


# ---------------------------------------------------------------- space

class TestConstraints:
    def test_default_assignment_feasible(self):
        assert violations({"dispatch_depth": 2, "steps_per_dispatch": 1,
                           "device_prefetch": 0}, CPU1) == []

    def test_pallas_requires_tpu(self):
        bad = violations({"pallas_sgd": True, "pallas_bn": True}, CPU1)
        assert len(bad) == 2 and all("TPU" in b for b in bad)
        tpu = dataclasses.replace(CPU1, platform="tpu")
        assert violations({"pallas_sgd": True}, tpu) == []

    def test_grad_compress_needs_dp_and_syncing_rung(self):
        assert violations({"grad_compress": "int8"}, CPU1)
        nosync = Workload(platform="tpu", dp=8, strategy="none")
        assert violations({"grad_compress": "bf16"}, nosync)
        ok = Workload(platform="tpu", dp=8, strategy="fused")
        assert violations({"grad_compress": "bf16"}, ok) == []

    def test_depth_vs_multiprocess_cadence(self):
        ctx = Workload(platform="tpu", dp=8, processes=2,
                       strategy="fused", collective_cadence=True)
        assert violations({"dispatch_depth": 2}, ctx)
        assert violations({"dispatch_depth": 0}, ctx) == []
        one_proc = dataclasses.replace(ctx, processes=1)
        assert violations({"dispatch_depth": 2}, one_proc) == []

    def test_grouped_dispatch_fallback_cells(self):
        assert violations({"steps_per_dispatch": 4,
                           "device_prefetch": 2}, CPU1)
        cad = dataclasses.replace(CPU1, collective_cadence=True)
        assert violations({"steps_per_dispatch": 4}, cad)


class TestSearchSpace:
    def test_cpu_single_process_space_has_schedule_knobs(self, cfg):
        # The acceptance floor: the vgg11 CPU smoke config must expose a
        # >=3-knob search (pallas knobs are off-TPU, grad_compress has
        # no dp>1 syncing rung -> both filtered by the constraints;
        # act_dtype is semantic-gated; remat is numerics-preserving so
        # it IS searchable by default).
        names = {k.name for k, _ in searchable_knobs(cfg, CPU1)}
        assert names == {"dispatch_depth", "steps_per_dispatch",
                         "device_prefetch", "remat"}

    def test_current_value_listed_first(self, cfg):
        cfg.dispatch_depth = 4
        for knob, cands in searchable_knobs(cfg, CPU1):
            assert cands[0] == getattr(cfg, knob.field)

    def test_semantic_knobs_gated(self, cfg, monkeypatch):
        base = {k.name for k, _ in searchable_knobs(cfg, CPU1)}
        assert "compute_dtype" not in base
        monkeypatch.setenv("TPU_DDP_TUNE_SEMANTIC", "1")
        gated = {k.name for k, _ in searchable_knobs(cfg, CPU1)}
        assert "compute_dtype" in gated
        # global_batch_size stays out even then: audit-only (values=())
        assert "global_batch_size" not in gated

    def test_env_pinned_knob_excluded(self, cfg, monkeypatch):
        monkeypatch.setenv("TPU_DDP_DISPATCH_DEPTH", "4")
        names = {k.name for k, _ in searchable_knobs(cfg, CPU1)}
        assert "dispatch_depth" not in names

    def test_knob_filter_parsing(self):
        only = parse_knob_filter("dispatch_depth=0|2, steps_per_dispatch")
        assert only == {"dispatch_depth": (0, 2),
                        "steps_per_dispatch": None}
        assert parse_knob_filter("") is None
        with pytest.raises(ValueError, match="unknown knob"):
            parse_knob_filter("warp_speed")

    def test_knob_filter_shrinks_space(self, cfg, monkeypatch):
        monkeypatch.setenv("TPU_DDP_TUNE_KNOBS",
                           "dispatch_depth=0|2,device_prefetch")
        space = searchable_knobs(cfg, CPU1)
        assert {k.name for k, _ in space} == {"dispatch_depth",
                                              "device_prefetch"}
        depth = dict((k.name, c) for k, c in space)["dispatch_depth"]
        assert set(depth) == {0, 2} and depth[0] == 2  # current first

    def test_space_version_tracks_registry(self, monkeypatch):
        v0 = space_version()
        import tpu_ddp.tune.space as space_mod
        monkeypatch.setattr(space_mod, "KNOBS", KNOBS[:-1])
        assert space_version() != v0


class TestFingerprint:
    def test_stable_and_discriminating(self, cfg):
        fp1 = fingerprint_for(cfg, "fused", None)
        fp2 = fingerprint_for(cfg, "fused", None)
        assert fp1.key() == fp2.key()
        bigger = TrainConfig(global_batch_size=512)
        assert fingerprint_for(bigger, "fused", None).key() != fp1.key()
        assert fingerprint_for(cfg, "zero", None).key() != fp1.key()

    def test_workload_for_reads_runtime(self, cfg, devices):
        ctx = workload_for(cfg, "part3", None)
        assert ctx.platform == "cpu" and ctx.processes == 1
        assert ctx.strategy == "fused"  # canonicalized alias
        cfg.check_replicas_every = 5
        assert workload_for(cfg, "fused", None).collective_cadence


# ---------------------------------------------------------------- cache

class TestCacheLifecycle:
    def test_store_then_hit(self, cfg, cache_dir):
        fp = fingerprint_for(cfg, "fused", None)
        path = tcache.store(fp, {"dispatch_depth": 4},
                            meta={"trials": 7})
        hit = tcache.load(fp)
        assert hit["overrides"] == {"dispatch_depth": 4}
        assert hit["meta"]["trials"] == 7
        assert hit["path"] == path

    def test_absent_is_a_plain_miss(self, cfg, cache_dir):
        assert tcache.load(fingerprint_for(cfg, "fused", None)) is None

    def test_corrupt_entry_quarantined(self, cfg, cache_dir):
        fp = fingerprint_for(cfg, "fused", None)
        path = tcache.store(fp, {})
        with open(path, "w") as f:
            f.write("{truncated")
        with pytest.warns(UserWarning, match="corrupt"):
            assert tcache.load(fp) is None
        assert not os.path.exists(path)
        assert os.path.exists(path + ".corrupt")

    def test_quarantine_never_overwrites_prior_evidence(self, cfg,
                                                        cache_dir):
        fp = fingerprint_for(cfg, "fused", None)
        for _ in range(2):
            path = tcache.store(fp, {})
            with open(path, "w") as f:
                f.write("not json")
            with pytest.warns(UserWarning):
                tcache.load(fp)
        assert os.path.exists(path + ".corrupt")
        assert os.path.exists(path + ".corrupt-2")

    def test_fingerprint_mismatch_quarantined(self, cfg, cache_dir):
        # A hand-copied entry sitting at another workload's key must be
        # rejected: applying it would tune the wrong workload.
        fp_a = fingerprint_for(cfg, "fused", None)
        fp_b = fingerprint_for(TrainConfig(global_batch_size=512),
                               "fused", None)
        src = tcache.store(fp_a, {"dispatch_depth": 0})
        os.makedirs(os.path.dirname(tcache.entry_path(fp_b)),
                    exist_ok=True)
        os.replace(src, tcache.entry_path(fp_b))
        with pytest.warns(UserWarning, match="different fingerprint"):
            assert tcache.load(fp_b) is None
        assert os.path.exists(tcache.entry_path(fp_b) + ".corrupt")

    def test_schema_bump_is_a_soft_miss(self, cfg, cache_dir):
        fp = fingerprint_for(cfg, "fused", None)
        path = tcache.store(fp, {"dispatch_depth": 0})
        with open(path) as f:
            payload = json.load(f)
        payload["schema_version"] = tcache.SCHEMA_VERSION + 1
        with open(path, "w") as f:
            json.dump(payload, f)
        assert tcache.load(fp) is None
        # NOT corruption: the stale file stays for the next store() to
        # overwrite — no .corrupt sibling appears.
        assert os.path.exists(path)
        assert not os.path.exists(path + ".corrupt")

    def test_unknown_override_keys_quarantined(self, cfg, cache_dir):
        fp = fingerprint_for(cfg, "fused", None)
        path = tcache.store(fp, {"retired_knob": 3})
        with pytest.warns(UserWarning, match="outside the knob registry"):
            assert tcache.load(fp) is None
        assert os.path.exists(path + ".corrupt")


# -------------------------------------------------------------- resolve

class TestResolve:
    def test_cached_mode_empty_cache_warns_and_defaults(self, cfg,
                                                        cache_dir):
        cfg.autotune = "cached"
        lines = []
        out = tune.resolve(cfg, strategy="fused", mesh=None,
                           log=lines.append)
        assert out.autotune == "off"
        assert out.dispatch_depth == cfg.dispatch_depth
        assert any("cached mode: no entry" in ln for ln in lines)

    def test_cached_mode_applies_stored_overrides(self, cfg, cache_dir):
        fp = fingerprint_for(cfg, "fused", None)
        tcache.store(fp, {"dispatch_depth": 0, "steps_per_dispatch": 8})
        cfg.autotune = "cached"
        lines = []
        out = tune.resolve(cfg, strategy="fused", mesh=None,
                           log=lines.append)
        assert (out.dispatch_depth, out.steps_per_dispatch) == (0, 8)
        assert any("cache hit: trials=0" in ln for ln in lines)
        assert cfg.dispatch_depth == 2  # original never mutated

    def test_env_pin_beats_cached_override(self, cfg, cache_dir,
                                           monkeypatch):
        fp = fingerprint_for(cfg, "fused", None)
        tcache.store(fp, {"dispatch_depth": 0})
        monkeypatch.setenv("TPU_DDP_DISPATCH_DEPTH", "4")
        cfg.dispatch_depth = 4  # what __post_init__ would have done
        # Same fingerprint (depth is not in the fingerprint), but the
        # explicit pin must survive the tuned override.
        cfg.autotune = "cached"
        lines = []
        out = tune.resolve(cfg, strategy="fused", mesh=None,
                           log=lines.append)
        assert out.dispatch_depth == 4
        assert any("pins the knob" in ln for ln in lines)

    def test_model_built_drops_model_level_overrides(self, cfg):
        out = tune.apply_overrides(
            cfg, {"pallas_bn": True, "dispatch_depth": 0},
            model_built=True, log=lambda s: None)
        assert out.pallas_bn is False and out.dispatch_depth == 0
        out2 = tune.apply_overrides(
            cfg, {"pallas_bn": True}, model_built=False,
            log=lambda s: None)
        assert out2.pallas_bn is True

    def test_apply_does_not_rerun_post_init(self, cache_dir,
                                            monkeypatch):
        # The dataclasses.replace trap: re-running __post_init__ would
        # re-read TPU_DDP_AUTOTUNE and re-arm the tuner (recursion) and
        # clobber tuned values with env. apply_overrides must not.
        monkeypatch.setenv("TPU_DDP_AUTOTUNE", "search")
        cfg = TrainConfig()
        assert cfg.autotune == "search"
        out = tune.apply_overrides(cfg, {"dispatch_depth": 1},
                                   log=lambda s: None)
        assert out.autotune == "off" and out.dispatch_depth == 1

    def test_search_mode_via_fake_runner_writes_cache(self, cfg,
                                                      cache_dir,
                                                      monkeypatch):
        # Full resolve(search) flow with the measurement faked out:
        # depth 0 measures fastest, so it must be searched, stored,
        # applied — and a second resolve must hit the cache (0 trials).
        class FakeRunner:
            def __init__(self, *a, **kw):
                self.trials = 0
                self.quarantined = []

            def evaluate(self, assignment, fidelity="short"):
                self.trials += 1
                return 10.0 + (5.0 if assignment.get(
                    "dispatch_depth", 2) == 0 else 0.0), None

        monkeypatch.setattr(tune, "TrialRunner", FakeRunner)
        cfg.autotune = "search"
        lines = []
        out = tune.resolve(cfg, strategy="fused", mesh=None,
                           log=lines.append)
        assert out.dispatch_depth == 0
        search_lines = [ln for ln in lines
                        if ln.startswith("[autotune] search:")]
        assert len(search_lines) == 1
        assert "overrides={\"dispatch_depth\": 0}" in search_lines[0]

        cfg2 = TrainConfig()
        cfg2.autotune = "search"
        lines2 = []
        out2 = tune.resolve(cfg2, strategy="fused", mesh=None,
                            log=lines2.append)
        assert out2.dispatch_depth == 0
        assert any("cache hit: trials=0" in ln for ln in lines2)

    def test_provenance_lines_parse(self, cfg, cache_dir, monkeypatch):
        # scripts/run_experiments.py's autotune stage greps the
        # provenance lines out of subprocess stdout; its regexes must
        # track the REAL lines resolve() emits, not a copy frozen in
        # the test. Drive resolve twice (search, then hit) and feed the
        # captured lines through the stage's own parser.
        from scripts.run_experiments import (_RE_TUNE_HIT,
                                             _RE_TUNE_SEARCH,
                                             _parse_autotune)

        class FakeRunner:
            def __init__(self, *a, **kw):
                self.trials = 0
                self.quarantined = []

            def evaluate(self, assignment, fidelity="short"):
                self.trials += 1
                return 10.0 + (5.0 if assignment.get(
                    "dispatch_depth", 2) == 0 else 0.0), None

        monkeypatch.setattr(tune, "TrialRunner", FakeRunner)
        cfg.autotune = "search"
        lines = []
        tune.resolve(cfg, strategy="fused", mesh=None, log=lines.append)
        search_out = "\n".join(lines)
        assert _RE_TUNE_SEARCH.search(search_out)
        parsed = _parse_autotune(search_out)
        assert parsed["searched"] and parsed["trials"] > 0
        assert parsed["overrides"] == {"dispatch_depth": 0}

        cfg2 = TrainConfig()
        cfg2.autotune = "search"
        lines2 = []
        tune.resolve(cfg2, strategy="fused", mesh=None,
                     log=lines2.append)
        hit_out = "\n".join(lines2)
        assert _RE_TUNE_HIT.search(hit_out)
        parsed2 = _parse_autotune(hit_out)
        assert parsed2["cache_hit"] and parsed2["trials"] == 0
        assert parsed2["overrides"] == parsed["overrides"]

    def test_multiprocess_search_refused(self, cfg, cache_dir,
                                         monkeypatch):
        import jax
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        cfg.autotune = "search"
        lines = []
        out = tune.resolve(cfg, strategy="fused", mesh=None,
                           log=lines.append)
        assert out.dispatch_depth == cfg.dispatch_depth
        assert any("refused under multi-process" in ln for ln in lines)


# --------------------------------------------------------------- search

def _space(cfg, names):
    return [(k, c) for k, c in searchable_knobs(cfg, CPU1)
            if k.name in names]


class TestSearchLogic:
    def test_grid_mode_for_two_knobs(self, cfg):
        calls = []

        def evaluate(assignment, fidelity):
            calls.append((dict(assignment), fidelity))
            sps = 10.0
            if assignment.get("dispatch_depth") == 4:
                sps += 2
            if assignment.get("device_prefetch") == 2:
                sps += 1
            return sps, None

        knobs = _space(cfg, {"dispatch_depth", "device_prefetch"})
        base = {k.field: c[0] for k, c in knobs}
        out = run_search(knobs, evaluate, base)
        assert out["mode"] == "grid"
        assert out["overrides"] == {"dispatch_depth": 4,
                                    "device_prefetch": 2}
        assert out["tuned_steps_per_sec"] >= out["default_steps_per_sec"]
        # grid = full cross product at short fidelity (4 x 2 = 8 cells)
        assert len([c for c in calls if c[1] == "short"]) == 8

    def test_coordinate_descent_for_three_knobs(self, cfg):
        def evaluate(assignment, fidelity):
            sps = 10.0
            sps += {0: 3, 1: 1, 2: 0, 4: 2}[
                assignment.get("dispatch_depth", 2)]
            sps += {1: 0, 4: 2, 8: 1}[
                assignment.get("steps_per_dispatch", 1)]
            return sps, None

        knobs = _space(cfg, {"dispatch_depth", "steps_per_dispatch",
                             "device_prefetch"})
        base = {k.field: c[0] for k, c in knobs}
        out = run_search(knobs, evaluate, base)
        assert out["mode"] == "coordinate_descent"
        assert out["overrides"]["dispatch_depth"] == 0
        assert out["overrides"]["steps_per_dispatch"] == 4

    def test_memoization_never_remeasures(self, cfg):
        seen = {}

        def evaluate(assignment, fidelity):
            key = (tuple(sorted(assignment.items())), fidelity)
            seen[key] = seen.get(key, 0) + 1
            return 10.0, None

        knobs = _space(cfg, {"dispatch_depth", "steps_per_dispatch",
                             "device_prefetch"})
        base = {k.field: c[0] for k, c in knobs}
        run_search(knobs, evaluate, base)
        assert max(seen.values()) == 1

    def test_quarantined_cells_counted_infeasible_cells_not(self, cfg):
        def evaluate(assignment, fidelity):
            d = assignment.get("dispatch_depth", 2)
            if d == 4:
                return None, "quarantined: XlaRuntimeError: boom"
            if d == 1:
                return None, "constraint: known-invalid"
            return 10.0 + (1.0 if d == 0 else 0.0), None

        knobs = _space(cfg, {"dispatch_depth", "device_prefetch"})
        base = {k.field: c[0] for k, c in knobs}
        out = run_search(knobs, evaluate, base)
        assert out["quarantined"] >= 1
        assert out["overrides"].get("dispatch_depth") == 0
        trials = [h for h in out["history"]
                  if h["reason"] is None
                  or h["reason"].startswith("quarantined")]
        assert out["trials"] == len(trials)

    def test_regression_guard_keeps_defaults(self, cfg):
        # Short windows lie (noise favors depth 0), the long confirm
        # tells the truth (default wins): the tuner must ship nothing.
        def evaluate(assignment, fidelity):
            d = assignment.get("dispatch_depth", 2)
            if fidelity == "short":
                return (12.0 if d == 0 else 10.0), None
            return (9.0 if d == 0 else 10.0), None

        knobs = _space(cfg, {"dispatch_depth", "device_prefetch"})
        base = {k.field: c[0] for k, c in knobs}
        out = run_search(knobs, evaluate, base)
        assert out["overrides"] == {}
        assert out["tuned_steps_per_sec"] == out["default_steps_per_sec"]

    def test_everything_infeasible_returns_defaults(self, cfg):
        def evaluate(assignment, fidelity):
            return None, "quarantined: OOM"

        knobs = _space(cfg, {"dispatch_depth", "device_prefetch"})
        base = {k.field: c[0] for k, c in knobs}
        out = run_search(knobs, evaluate, base)
        assert out["overrides"] == {}

    def test_empty_space(self):
        out = run_search([], lambda a, f: (1.0, None), {})
        assert out == {"overrides": {}, "default_steps_per_sec": None,
                       "tuned_steps_per_sec": None, "trials": 0,
                       "quarantined": 0, "mode": "empty", "history": []}


# ------------------------------------------------------- timing helpers

class TestTimingHelpers:
    def test_timed_window_requires_iters(self):
        with pytest.raises(ValueError, match="iters"):
            timed_window_s(lambda: None, 0)

    def test_median_and_samples(self):
        ticks = iter(range(100))

        def run():
            return next(ticks)

        synced = []
        median, samples = warm_then_median_s(
            run, iters=2, windows=3, warmup=1, sync=synced.append)
        assert len(samples) == 3
        assert median == sorted(samples)[1]
        # one sync for warmup + one per window, on the LAST call's value
        assert len(synced) == 4

    def test_default_sync_tolerates_none(self):
        median, samples = warm_then_median_s(lambda: None, iters=1,
                                             windows=1)
        assert len(samples) == 1 and median >= 0


# -------------------------------------------- acceptance smoke (slow)

@pytest.mark.slow
def test_search_acceptance_smoke(tmp_path, monkeypatch):
    """The ISSUE acceptance cell: TPU_DDP_AUTOTUNE=search on the vgg11
    CPU smoke config completes a >=3-knob search in under 120 s, writes
    a cache entry, and a second run hits the cache (0 trials) with
    identical overrides."""
    import jax

    from tpu_ddp.parallel.mesh import make_mesh

    for key in list(os.environ):
        if key.startswith("TPU_DDP_"):
            monkeypatch.delenv(key)
    monkeypatch.setenv("TPU_DDP_TUNE_CACHE_DIR", str(tmp_path / "tune"))
    monkeypatch.setenv("TPU_DDP_TUNE_ITERS", "3")
    monkeypatch.setenv("TPU_DDP_TUNE_WINDOWS", "2")
    monkeypatch.setenv("TPU_DDP_AUTOTUNE", "search")

    mesh = make_mesh(jax.devices()[:1])
    cfg = TrainConfig.preset("vgg11_cifar10", global_batch_size=8)
    assert cfg.autotune == "search"
    ctx = workload_for(cfg, "fused", mesh)
    assert len(searchable_knobs(cfg, ctx)) >= 3

    lines = []
    t0 = time.perf_counter()
    tuned = tune.resolve(cfg, strategy="fused", mesh=mesh,
                         log=lines.append)
    wall = time.perf_counter() - t0
    assert wall < 120, f"search took {wall:.1f}s (budget 120s)"
    search_lines = [ln for ln in lines
                    if ln.startswith("[autotune] search:")]
    assert len(search_lines) == 1

    cfg2 = TrainConfig.preset("vgg11_cifar10", global_batch_size=8)
    lines2 = []
    rerun = tune.resolve(cfg2, strategy="fused", mesh=mesh,
                         log=lines2.append)
    assert any("cache hit: trials=0" in ln for ln in lines2)
    for field in ("dispatch_depth", "steps_per_dispatch",
                  "device_prefetch"):
        assert getattr(rerun, field) == getattr(tuned, field)
