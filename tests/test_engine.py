"""Engine tests: epoch loop, instrumentation, eval semantics
(reference train_model/test_model, part1/main.py:52-111)."""

import re

import jax.numpy as jnp
import numpy as np

from tpu_ddp.data.loader import DataLoader
from tpu_ddp.models.vgg import VGGModel
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig
from tpu_ddp.utils.timing import IterationTimer


def tiny_trainer(**kw):
    model = VGGModel(name="tiny", cfg=(8, "M", 16, "M"),
                     compute_dtype=jnp.float32)
    return Trainer(model, TrainConfig(**kw), strategy="none")


def separable_batches(n_batches=8, bs=32, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        y = rng.integers(0, 10, size=bs).astype(np.int32)
        x = rng.normal(0, 0.1, size=(bs, 4, 4, 3)).astype(np.float32)
        x[np.arange(bs), y % 4, y // 4 % 4, :] += 3.0  # class-dependent spike
        out.append((x, y))
    return out


def test_loss_decreases_on_learnable_data():
    trainer = tiny_trainer(learning_rate=0.05)
    state = trainer.init_state()
    batches = separable_batches(n_batches=30)
    first = last = None
    for x, y in batches:
        xb, yb, wb = trainer.put_batch(x, y)
        state, loss = trainer.train_step(state, xb, yb, wb)
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < first, (first, last)


def test_train_epoch_logging_cadence_and_timing():
    trainer = tiny_trainer(log_every=2, timing_first_iter=1,
                           timing_last_iter=3)
    state = trainer.init_state()
    lines = []
    state, stats = trainer.train_epoch(state, separable_batches(6),
                                       epoch=0, log=lines.append)
    loss_lines = [l for l in lines if "loss:" in l]
    assert len(loss_lines) == 3  # iters 2, 4, 6 with log_every=2
    timing_lines = [l for l in lines if "timing over iterations" in l]
    assert len(timing_lines) == 1
    assert stats["timed_iters"] == 3
    assert stats["avg_iter_ns"] > 0
    assert stats["iters"] == 6


def test_max_iters_caps_epoch():
    trainer = tiny_trainer(max_iters=2)
    state = trainer.init_state()
    _, stats = trainer.train_epoch(state, separable_batches(6), log=lambda s: None)
    assert stats["iters"] == 2


def test_evaluate_reports_per_batch_avg_loss_and_accuracy():
    trainer = tiny_trainer()
    state = trainer.init_state()
    batches = separable_batches(4, bs=16, seed=3)
    lines = []
    stats = trainer.evaluate(state, batches, log=lines.append)
    assert stats["seen"] == 64
    assert 0.0 <= stats["test_accuracy"] <= 1.0
    # avg over batches, not samples (reference part1/main.py:108)
    assert re.search(r"average loss", lines[0])


def test_iteration_timer_window():
    t = IterationTimer(first_iter=1, last_iter=3)
    for it in range(5):
        t.start()
        t.stop(it)
    assert t.count == 3
    assert t.total_ns >= 0
    assert "iterations 1-3" in t.report()


def test_dataloader_shapes_and_determinism():
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, size=(100, 32, 32, 3)).astype(np.uint8)
    labels = rng.integers(0, 10, size=100).astype(np.int32)
    dl = DataLoader(imgs, labels, batch_size=32, augment=True)
    dl.set_epoch(0)
    b1 = [x.copy() for x, _ in dl]
    assert [x.shape[0] for x in b1] == [32, 32, 32, 4]  # drop_last=False
    assert b1[0].dtype == np.float32
    dl.set_epoch(0)
    b2 = [x for x, _ in dl]
    np.testing.assert_array_equal(b1[0], b2[0])  # same epoch -> same crops
    dl.set_epoch(1)
    b3 = [x for x, _ in dl]
    assert not np.array_equal(b1[0], b3[0])  # reshuffled augmentation


class _PerExampleModel:
    """Tiny linear model with NO batch statistics: its predictions are
    per-example, so eval metrics must be EXACTLY split-invariant. (The
    VGG family's batch-stat BN computes per-shard statistics under
    sharded eval — the documented caveat, engine.py:evaluate.)"""

    def init(self, key):
        import jax
        k1, k2 = jax.random.split(key)
        return {"w": 0.1 * jax.random.normal(k1, (48, 10), jnp.float32),
                "b": 0.01 * jax.random.normal(k2, (10,), jnp.float32)}

    def apply(self, params, x):
        flat = x.reshape(x.shape[0], -1).astype(jnp.float32)
        return flat @ params["w"] + params["b"]


class TestShardedEval:
    """Opt-in dp-sharded eval (evaluate(sharded=True)): identical
    metrics to the reference-faithful replicated pass, 1/N per-device
    compute. Default stays replicated (part2/part2b/main.py:89-93)."""

    def _mesh_trainer(self, devices, strategy="fused"):
        from tpu_ddp.parallel.mesh import make_mesh
        mesh = make_mesh(devices[:4])
        return Trainer(_PerExampleModel(), TrainConfig(),
                       strategy=strategy, mesh=mesh)

    def _batches(self):
        # Includes a ragged batch (13 % 4 != 0): wrap-padding rows must
        # carry weight 0 in the sharded path.
        out = separable_batches(n_batches=2, bs=32, seed=3)
        rng = np.random.default_rng(9)
        y = rng.integers(0, 10, size=13).astype(np.int32)
        x = rng.normal(0, 0.1, size=(13, 4, 4, 3)).astype(np.float32)
        out.append((x, y))
        return out

    def test_matches_replicated(self, devices):
        tr = self._mesh_trainer(devices)
        state = tr.init_state()
        batches = self._batches()
        repl = tr.evaluate(state, batches, log=lambda s: None)
        shrd = tr.evaluate(state, batches, log=lambda s: None,
                           sharded=True)
        assert shrd["seen"] == repl["seen"] == 77
        assert shrd["correct"] == repl["correct"]
        np.testing.assert_allclose(shrd["test_loss"], repl["test_loss"],
                                   rtol=1e-5)

    def test_loader_weight_triples_mask_examples(self, devices):
        """(images, labels, weights) triples from a process-sharded
        loader: weight-0 rows contribute nothing to loss/correct/seen —
        evaluating a batch with its tail zero-weighted equals evaluating
        the batch without the tail."""
        tr = self._mesh_trainer(devices)
        state = tr.init_state()
        rng = np.random.default_rng(5)
        x = rng.normal(0, 0.1, size=(16, 4, 4, 3)).astype(np.float32)
        y = rng.integers(0, 10, size=16).astype(np.int32)
        w = np.ones(16, np.float32)
        w[12:] = 0.0  # the sampler wrap-padding marker
        masked = tr.evaluate(state, [(x, y, w)], log=lambda s: None,
                             sharded=True)
        plain = tr.evaluate(state, [(x[:12], y[:12])],
                            log=lambda s: None, sharded=True)
        assert masked["seen"] == plain["seen"] == 12
        assert masked["correct"] == plain["correct"]
        np.testing.assert_allclose(masked["test_loss"],
                                   plain["test_loss"], rtol=1e-5)

    def test_replicated_eval_honors_weight_triples(self, devices):
        """A weights-carrying loader fed to the REPLICATED eval must not
        count wrap-padding rows as real examples (they are dropped
        host-side), matching the sharded path's masking."""
        tr = self._mesh_trainer(devices)
        state = tr.init_state()
        rng = np.random.default_rng(6)
        x = rng.normal(0, 0.1, size=(16, 4, 4, 3)).astype(np.float32)
        y = rng.integers(0, 10, size=16).astype(np.int32)
        w = np.ones(16, np.float32)
        w[12:] = 0.0
        repl = tr.evaluate(state, [(x, y, w)], log=lambda s: None)
        plain = tr.evaluate(state, [(x[:12], y[:12])],
                            log=lambda s: None)
        assert repl["seen"] == plain["seen"] == 12
        assert repl["correct"] == plain["correct"]
        np.testing.assert_allclose(repl["test_loss"],
                                   plain["test_loss"], rtol=1e-6)

    def test_matches_replicated_under_fsdp(self, devices):
        tr = self._mesh_trainer(devices, strategy="fsdp")
        state = tr.init_state()
        batches = self._batches()
        repl = tr.evaluate(state, batches, log=lambda s: None)
        shrd = tr.evaluate(state, batches, log=lambda s: None,
                           sharded=True)
        assert shrd["correct"] == repl["correct"]
        np.testing.assert_allclose(shrd["test_loss"], repl["test_loss"],
                                   rtol=1e-5)


class TestMultiStep:
    """build_multi_step: K scanned steps == K sequential train_steps."""

    def test_scan_matches_sequential(self, devices):
        from tpu_ddp.parallel.mesh import make_mesh
        import jax

        model = _PerExampleModel()
        batches = separable_batches(n_batches=4, bs=16, seed=7)

        def run_sequential():
            tr = Trainer(model, TrainConfig(), strategy="fused",
                         mesh=make_mesh(devices[:2]))
            state = tr.init_state()
            losses = []
            for bx, by in batches:
                state, loss = tr.train_step(state, *tr.put_batch(bx, by))
                losses.append(np.ravel(np.asarray(loss)))
            return jax.device_get(state.params), np.stack(losses)

        def run_scanned():
            tr = Trainer(model, TrainConfig(), strategy="fused",
                         mesh=make_mesh(devices[:2]))
            state = tr.init_state()
            multi = tr.build_multi_step(4)
            xs = np.stack([b[0] for b in batches])
            ys = np.stack([b[1] for b in batches])
            state, losses = multi(state, *tr.put_batches(xs, ys))
            return jax.device_get(state.params), np.asarray(losses)

        p_seq, l_seq = run_sequential()
        p_scan, l_scan = run_scanned()
        np.testing.assert_allclose(l_scan, l_seq, rtol=1e-6, atol=1e-7)
        import jax as _jax
        for a, b in zip(_jax.tree.leaves(p_seq), _jax.tree.leaves(p_scan)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-6, atol=1e-7)

    def test_single_device_and_validation(self):
        tr = tiny_trainer()
        with np.testing.assert_raises(ValueError):
            tr.build_multi_step(0)
        batches = separable_batches(n_batches=2, bs=8, seed=1)
        xs = np.stack([b[0] for b in batches])
        ys = np.stack([b[1] for b in batches])
        multi = tr.build_multi_step(2)
        state, losses = multi(tr.init_state(), *tr.put_batches(xs, ys))
        assert losses.shape == (2,)
        assert np.isfinite(np.asarray(losses)).all()


class TestEpochMultiDispatch:
    """train_epoch with cfg.steps_per_dispatch > 1: same losses and
    iteration count as the per-step loop, ragged tail included."""

    def test_matches_per_step_epoch(self):
        batches = separable_batches(n_batches=7, bs=16, seed=11)
        # Ragged final batch exercises the single-step fallback.
        rng = np.random.default_rng(12)
        y = rng.integers(0, 10, size=9).astype(np.int32)
        x = rng.normal(0, 0.1, size=(9, 4, 4, 3)).astype(np.float32)
        batches.append((x, y))

        logs = {}
        stats = {}
        for spd in (1, 4):
            tr = tiny_trainer(steps_per_dispatch=spd, log_every=2)
            lines = []
            state, st = tr.train_epoch(tr.init_state(), batches,
                                       epoch=0, log=lines.append)
            logs[spd] = [ln for ln in lines if "loss:" in ln]
            stats[spd] = st
        assert stats[1]["iters"] == stats[4]["iters"] == 8
        # Same loss prints at the same cadence (losses are bit-equal:
        # the scanned step is the same program).
        assert logs[4] == logs[1]

    def test_respects_max_iters(self):
        tr = tiny_trainer(steps_per_dispatch=4, max_iters=5)
        batches = separable_batches(n_batches=10, bs=8, seed=3)
        _, st = tr.train_epoch(tr.init_state(), batches,
                               log=lambda s: None)
        assert st["iters"] == 5
