"""The serving subsystem (tpu_ddp/serve/): paged KV pool accounting,
continuous-batching scheduler invariants (docs/DESIGN.md §19), and the
engine's exactness guarantee — a request served through the paged pool
under continuous batching yields EXACTLY the tokens ``generate()``
yields, which in turn is pinned against ``model.apply`` in
tests/test_generate.py. The train→serve round trip (LM trainer
checkpoint → ``ServeEngine.from_checkpoint`` → logprob parity with
``apply``) closes the loop end to end.

Every engine in the fast tier shares ONE cache geometry
(block_size=8, blocks_per_seq=8 at max_seq_len=64), so they all share
the two memoized jitted step programs (engine.py) — the whole file
compiles the decode/prefill steps once.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.generate import generate
from tpu_ddp.models.transformer import make_transformer, rope
from tpu_ddp.serve import (
    PagedKVPool,
    Request,
    Scheduler,
    ServeEngine,
    make_shared_prefix_workload,
    make_workload,
    run_load,
)
from tpu_ddp.serve.loadgen import poisson_arrivals
from tpu_ddp.utils.metrics import MetricsLogger

# One geometry for every fast-tier engine: the jitted steps are
# memoized on (model, block_size, blocks_per_seq), so this is one
# decode + one prefill compile for the whole module.
GEOM = dict(num_slots=4, block_size=8, prefill_chunk=8)


@pytest.fixture(scope="module")
def model():
    return make_transformer("TransformerLM-tiny", max_seq_len=64,
                            compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def _engine(model, params, **kw):
    cfg = dict(GEOM)
    cfg.update(kw)
    return ServeEngine(model, params, **cfg)


def _prompt(L, seed=0):
    return np.random.default_rng(seed).integers(0, 1024, size=L,
                                                dtype=np.int64)


def _ref_greedy(model, params, prompt, n):
    """generate()'s continuation — the engine must match it exactly."""
    out = generate(model, params,
                   np.asarray(prompt, np.int32)[None], n)
    return np.asarray(out)[0]


def _ref_logprobs(model, params, prompt, tokens):
    """log P(token_i | prefix) straight from model.apply — the
    distribution the trainer optimized."""
    seq = np.concatenate([np.asarray(prompt, np.int32),
                          np.asarray(tokens, np.int32)])
    logits = np.asarray(model.apply(params, jnp.asarray(seq[None])))[0]
    lps = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), axis=-1)
    p = len(prompt)
    return np.array([float(lps[p - 1 + i, t])
                     for i, t in enumerate(tokens)])


class TestPagedPool:
    def test_alloc_free_roundtrip(self, model):
        pool = PagedKVPool(model, num_blocks=9, block_size=8)
        assert pool.total_usable == 8 and pool.free_count == 8
        got = [pool.alloc() for _ in range(8)]
        assert len(set(got)) == 8
        assert PagedKVPool.NULL_BLOCK not in got
        assert pool.free_count == 0
        with pytest.raises(RuntimeError, match="exhausted"):
            pool.alloc()
        pool.free(got)
        assert pool.free_count == 8

    def test_free_misuse_is_loud(self, model):
        pool = PagedKVPool(model, num_blocks=5, block_size=8)
        b = pool.alloc()
        pool.free([b])
        with pytest.raises(ValueError, match="double free"):
            pool.free([b])
        with pytest.raises(ValueError, match="null block"):
            pool.free([PagedKVPool.NULL_BLOCK])
        with pytest.raises(ValueError, match="out of range"):
            pool.free([99])

    def test_geometry_validation(self, model):
        with pytest.raises(ValueError, match="null block"):
            PagedKVPool(model, num_blocks=1, block_size=8)
        with pytest.raises(ValueError, match="block_size"):
            PagedKVPool(model, num_blocks=4, block_size=0)
        assert PagedKVPool(model, 4, 8).blocks_for(17) == 3
        assert PagedKVPool(model, 4, 8).blocks_for(16) == 2

    def test_cache_dtype_rides_memory_policy(self, model):
        # Same vocabulary as the training-side activation policy
        # (memory/policy.py): "compute" preserves exactness, "bf16"
        # halves cache bytes under this f32 model.
        assert PagedKVPool(model, 4, 8, "compute").k.dtype \
            == jnp.float32
        assert PagedKVPool(model, 4, 8, "bf16").k.dtype == jnp.bfloat16
        with pytest.raises(ValueError):
            PagedKVPool(model, 4, 8, "fp4")


class TestScheduler:
    def _req(self, rid, p_len, max_new):
        return Request(rid=rid, prompt=np.zeros(p_len, np.int32),
                       max_new_tokens=max_new)

    def test_infeasible_request_rejected_at_enqueue(self, model):
        sched = Scheduler(PagedKVPool(model, 3, 8), num_slots=2)
        with pytest.raises(ValueError, match="KV blocks"):
            sched.enqueue(self._req(0, 20, 20))  # 5 blocks > 2 usable

    def test_fifo_head_blocking_and_reservation(self, model):
        # Pool of 4 usable blocks; A reserves all 4 worst-case, so B
        # (needing only 1) must NOT jump the... actually must not be
        # admitted at all while A's reservation holds the pool.
        sched = Scheduler(PagedKVPool(model, 5, 8), num_slots=2)
        a, b = self._req(0, 8, 24), self._req(1, 4, 4)
        sched.enqueue(a)
        sched.enqueue(b)
        admitted = sched.admit()
        assert len(admitted) == 1
        assert sched.slots[admitted[0]].request is a
        assert list(sched.queue) == [b]  # head-blocked, not skipped
        assert sched.accounting_ok()
        # Retiring A releases blocks AND reservation; B admits next.
        sched.retire(admitted[0])
        admitted = sched.admit()
        assert len(admitted) == 1
        assert sched.slots[admitted[0]].request is b
        assert sched.accounting_ok()

    def test_static_mode_drains_before_refilling(self, model):
        sched = Scheduler(PagedKVPool(model, 33, 8), num_slots=2,
                          mode="static")
        for i in range(3):
            sched.enqueue(self._req(i, 4, 4))
        first = sched.admit()
        assert len(first) == 2          # fill every slot...
        assert sched.admit() == []      # ...then nothing while live
        for i in first:
            sched.retire(i)
        assert len(sched.admit()) == 1  # refill only after full drain

    def test_mode_validation(self, model):
        with pytest.raises(ValueError, match="mode"):
            Scheduler(PagedKVPool(model, 3, 8), 2, mode="dynamic")


class TestEngineParity:
    def test_greedy_matches_generate_across_mixed_batch(self, model,
                                                        params):
        """The tentpole guarantee: continuous batching + chunked
        prefill + the paged pool change WHEN work runs, never WHAT is
        computed. Prompt lengths straddle the prefill chunk (8) and
        block size (8) boundaries; generation budgets differ so slots
        retire and refill mid-flight."""
        eng = _engine(model, params)
        cases = [(3, 6), (8, 6), (11, 6), (20, 4), (9, 12), (5, 6)]
        reqs = [eng.submit(_prompt(L, seed=i), n)
                for i, (L, n) in enumerate(cases)]
        eng.run()
        for i, ((L, n), req) in enumerate(zip(cases, reqs)):
            assert req.done and not req.cancelled
            np.testing.assert_array_equal(
                np.asarray(req.tokens),
                _ref_greedy(model, params, _prompt(L, seed=i), n),
                err_msg=f"request {i} (prompt {L}, max_new {n})")
        # Drained engine: every page back in the pool.
        assert eng.pool.free_count == eng.pool.total_usable
        assert eng.sched.accounting_ok()

    def test_logprobs_match_apply(self, model, params):
        eng = _engine(model, params)
        prompt = _prompt(10, seed=3)
        req = eng.submit(prompt, 6)
        eng.run()
        want = _ref_logprobs(model, params, prompt, req.tokens)
        np.testing.assert_allclose(np.asarray(req.logprobs), want,
                                   rtol=1e-4, atol=1e-4)

    def test_static_mode_same_tokens(self, model, params):
        # The baseline scheduler changes admission timing only.
        eng = _engine(model, params, mode="static")
        cases = [(4, 5), (9, 3), (6, 8)]
        reqs = [eng.submit(_prompt(L, seed=10 + i), n)
                for i, (L, n) in enumerate(cases)]
        eng.run()
        for i, ((L, n), req) in enumerate(zip(cases, reqs)):
            np.testing.assert_array_equal(
                np.asarray(req.tokens),
                _ref_greedy(model, params, _prompt(L, seed=10 + i), n))

    def test_bf16_cache_runs(self, model, params):
        # Semantic knob: not exactness-preserving, but must produce a
        # full-length generation through the same programs.
        eng = _engine(model, params, cache_dtype="bf16")
        assert eng.pool.k.dtype == jnp.bfloat16
        req = eng.submit(_prompt(6, seed=4), 5)
        eng.run()
        assert req.done and len(req.tokens) == 5


class TestLifecycle:
    def test_no_block_leak_across_120_requests(self, model, params):
        """The acceptance drill: a pool far smaller than the offered
        work, >= 100 requests admitted and retired through it, and the
        free count returns to exactly total_usable — no leaked, no
        double-freed page, with the §19 identity holding at every
        engine step."""
        eng = _engine(model, params, num_blocks=9)  # 8 usable pages
        rng = np.random.default_rng(0)
        reqs = [eng.submit(rng.integers(0, 1024, size=int(p)), int(n))
                for p, n in zip(rng.integers(3, 9, size=120),
                                rng.integers(2, 7, size=120))]
        steps = 0
        while eng.step():
            steps += 1
            assert eng.sched.accounting_ok(), f"leak at step {steps}"
        assert all(r.done and not r.cancelled for r in reqs)
        assert eng.pool.free_count == eng.pool.total_usable == 8
        assert eng.metrics.counters["serve_admitted"] == 120
        assert eng.metrics.counters["serve_retired"] == 120

    def test_completion_order_is_fifo_under_pressure(self, model,
                                                     params):
        # 2 usable pages, each request worst-cases to 2: strictly one
        # live request at a time, so completion order == submit order
        # (the no-starvation invariant, observed from the outside).
        eng = _engine(model, params, num_blocks=3)
        reqs = [eng.submit(_prompt(6, seed=20 + i), 6)
                for i in range(3)]
        eng.run()
        assert all(r.done for r in reqs)
        finished = [r.finished_at for r in reqs]
        assert finished == sorted(finished)

    def test_cancel_queued_and_live(self, model, params):
        eng = _engine(model, params, num_blocks=3)  # one live at a time
        a = eng.submit(_prompt(6, seed=30), 6)
        b = eng.submit(_prompt(6, seed=31), 6)
        assert eng.cancel(b)           # still queued: just drop it
        eng.step()                     # a is admitted + prefilling
        assert eng.cancel(a)           # live: slot + pages come back
        assert a.cancelled and b.cancelled
        assert eng.pool.free_count == eng.pool.total_usable
        assert eng.sched.accounting_ok()
        assert not eng.cancel(a)       # nothing left to cancel
        assert eng.metrics.counters["serve_cancelled"] == 2
        eng.run()
        assert a.tokens == [] or len(a.tokens) < 6  # never completed

    def test_cancel_mid_prefill_frees_reserved_blocks(self, model,
                                                      params):
        """Regression: a request cancelled BETWEEN prefill chunks (its
        prompt spans several) must hand back every reserved page, not
        just the ones already written — a leak here strangles the pool
        one cancelled long prompt at a time."""
        eng = _engine(model, params)
        a = eng.submit(_prompt(20, seed=32), 6)  # 3 chunks of 8
        eng.step()                     # admitted + first chunk only
        s = [x for x in eng.sched.slots if x is not None][0]
        assert s.phase == "prefill" and s.prefill_done < 20
        assert eng.cancel(a)
        assert a.cancelled and a.done
        assert eng.pool.free_count == eng.pool.total_usable
        assert eng.sched.accounting_ok()
        assert not eng.step()          # engine fully idle again

    def test_cancel_drops_pending_disagg_edge_transfer(self, model,
                                                       params):
        """Regression (fleet half of the same bug): a request whose
        prefill finished but whose KV transfer still sits on the
        prefill->decode edge must be cancellable — the transfer is
        dropped and never adopted into the decode pool."""
        from tpu_ddp.fleet import DisaggEngine
        # Decode pool of 2 usable pages: exactly one live request.
        eng = DisaggEngine(model, params, num_blocks=3, **GEOM)
        a = eng.submit(_prompt(9, seed=33), 6)   # 2 blocks worst-case
        b = eng.submit(_prompt(9, seed=34), 6)
        # Step until b's transfer is parked on the edge (a holds the
        # whole decode pool, so the adopter's reservation check gates).
        for _ in range(8):
            eng.step()
            if eng.edge.queue:
                break
        assert [t.request for t in eng.edge.queue] == [b]
        assert eng.cancel(b)
        assert b.cancelled and b.done
        assert len(eng.edge.queue) == 0
        assert eng.edge.stats()["dropped"] == 1
        assert eng.accounting_ok()
        eng.run()                       # a finishes untouched
        assert a.done and not a.cancelled and len(a.tokens) == 6
        # Every page of both pools comes home; b was never adopted.
        assert eng.pool.free_count == eng.pool.total_usable
        assert eng.prefill_pool.free_count \
            == eng.prefill_pool.total_usable
        assert eng.metrics.counters["fleet_adopted"] == 1

    def test_eos_stops_early_and_frees_slot(self, model, params):
        prompt = _prompt(5, seed=40)
        full = _ref_greedy(model, params, prompt, 6)
        eos = int(full[2])
        eng = _engine(model, params)
        req = eng.submit(prompt, 6, eos_id=eos)
        eng.run()
        assert req.done
        np.testing.assert_array_equal(np.asarray(req.tokens), full[:3])
        assert eng.pool.free_count == eng.pool.total_usable

    def test_streaming_callback_order(self, model, params):
        seen = []
        eng = _engine(model, params)
        req = eng.submit(_prompt(7, seed=41), 5, on_token=seen.append)
        eng.run()
        assert seen == req.tokens and len(seen) == 5
        assert req.ttft_s is not None and req.ttft_s >= 0

    def test_submit_validation(self, model, params):
        eng = _engine(model, params)
        with pytest.raises(ValueError, match=">= 1 token"):
            eng.submit(np.zeros(0, np.int32), 4)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(_prompt(4), 0)
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.submit(_prompt(60), 10)
        with pytest.raises(ValueError, match="temperature"):
            eng.submit(_prompt(4), 2, temperature=-0.5)

    def test_infeasible_submit_names_the_pool(self, model, params):
        eng = _engine(model, params, num_blocks=3)
        with pytest.raises(ValueError, match="KV blocks"):
            eng.submit(_prompt(10), 20)  # 4 worst-case > 2 usable


class TestSampling:
    def test_seeded_sampling_survives_rebatching(self, model, params):
        """Sampling is keyed by (request seed, absolute position) —
        stateless — so the SAME request produces the SAME tokens no
        matter which neighbors share its batch. This is the property
        that makes serving results reproducible under load."""
        prompt = _prompt(6, seed=50)
        alone = _engine(model, params)
        r1 = alone.submit(prompt, 6, temperature=1.0, seed=7)
        alone.run()
        crowded = _engine(model, params)
        for i in range(3):  # different neighbors, different seeds
            crowded.submit(_prompt(5 + i, seed=60 + i), 4,
                           temperature=1.0, seed=100 + i)
        r2 = crowded.submit(prompt, 6, temperature=1.0, seed=7)
        crowded.run()
        assert r1.tokens == r2.tokens

    def test_different_seeds_differ(self, model, params):
        prompt = _prompt(6, seed=51)
        eng = _engine(model, params)
        a = eng.submit(prompt, 6, temperature=1.0, seed=1)
        b = eng.submit(prompt, 6, temperature=1.0, seed=2)
        eng.run()
        assert a.tokens != b.tokens


class TestKnobs:
    def test_env_defaults_flow_into_engine(self, model, params,
                                           monkeypatch):
        monkeypatch.setenv("TPU_DDP_SERVE_SLOTS", "4")
        monkeypatch.setenv("TPU_DDP_SERVE_BLOCK", "8")
        monkeypatch.setenv("TPU_DDP_SERVE_PREFILL_CHUNK", "8")
        monkeypatch.setenv("TPU_DDP_SERVE_CACHE_DTYPE", "f32")
        eng = ServeEngine(model, params)  # no explicit knobs
        assert eng.num_slots == 4
        assert eng.block_size == 8
        assert eng.prefill_chunk == 8
        assert eng.pool.dtype == jnp.float32

    def test_junk_env_values_rejected(self, monkeypatch):
        from tpu_ddp.utils.config import TrainConfig
        monkeypatch.setenv("TPU_DDP_SERVE_CACHE_DTYPE", "fp4")
        with pytest.raises(ValueError,
                           match="TPU_DDP_SERVE_CACHE_DTYPE"):
            TrainConfig()
        monkeypatch.delenv("TPU_DDP_SERVE_CACHE_DTYPE")
        monkeypatch.setenv("TPU_DDP_SERVE_SLOTS", "0")
        with pytest.raises(ValueError, match="TPU_DDP_SERVE_SLOTS"):
            TrainConfig()


class TestMetrics:
    def test_counters_and_gauges(self, model, params):
        m = MetricsLogger(None)
        eng = _engine(model, params, metrics=m)
        for i in range(3):
            eng.submit(_prompt(4 + i, seed=70 + i), 3)
        eng.run()
        assert m.counters["serve_submitted"] == 3
        assert m.counters["serve_admitted"] == 3
        assert m.counters["serve_retired"] == 3
        assert m.gauge_summary("serve_ttft_ms")["count"] == 3
        occ = m.gauge_summary("serve_slot_occupancy")
        assert occ is not None and 0.0 <= occ["max"] <= 1.0
        assert m.gauge_summary("serve_queue_depth") is not None


class TestLoadgen:
    def test_arrivals_and_workload_are_seeded(self):
        a = poisson_arrivals(16, rate=5.0, seed=3)
        b = poisson_arrivals(16, rate=5.0, seed=3)
        np.testing.assert_array_equal(a, b)
        assert np.all(np.diff(a) > 0) and np.all(a > 0)
        w1 = make_workload(8, 1024, seed=1)
        w2 = make_workload(8, 1024, seed=1)
        assert w1 == w2
        assert all(4 <= len(s.prompt) <= 16 for s in w1)

    def test_run_load_measures_and_completes(self, model, params):
        specs = make_workload(6, 1024, seed=2, prompt_len=(3, 9),
                              max_new=(2, 6))
        m = run_load(_engine(model, params), specs, rate=500.0,
                     slo_ttft_ms=1e4)
        assert m["n_requests"] == 6
        assert m["total_tokens"] == sum(s.max_new_tokens for s in specs)
        assert m["ttft_p50_ms"] <= m["ttft_p99_ms"]
        # The full latency anatomy: e2e covers TTFT, and with every
        # spec generating >= 2 tokens TPOT is measurable everywhere.
        assert m["e2e_p50_ms"] <= m["e2e_p99_ms"]
        assert m["e2e_p99_ms"] >= m["ttft_p99_ms"]
        assert m["tpot_p50_ms"] is not None
        assert 0.0 <= m["tpot_p50_ms"] <= m["tpot_p99_ms"]
        assert m["tpot_mean_ms"] > 0.0
        assert m["slo_attained"] == 1.0  # absurdly lax SLO
        assert m["goodput_tokens_per_sec"] == m["tokens_per_sec"]

    def test_shared_prefix_workload_is_seeded_and_shared(self):
        w1 = make_shared_prefix_workload(6, 1024, seed=3, prefix_len=16)
        w2 = make_shared_prefix_workload(6, 1024, seed=3, prefix_len=16)
        assert w1 == w2
        heads = {s.prompt[:16] for s in w1}
        assert len(heads) == 1           # one shared system prompt
        assert len({s.prompt for s in w1}) > 1  # distinct tails

    @pytest.mark.slow  # wall-clock load drill: two timed runs at 2x
    # saturation plus a calibration run (~tens of seconds)
    def test_continuous_beats_static_goodput_under_overload(
            self, model, params):
        """The subsystem's reason to exist, as a regression test: at
        2x the measured saturation rate and a TTFT SLO derived from an
        unloaded probe, continuous batching delivers at least the
        goodput of static batching (the sweep artifact enforces
        strictly-greater; >= here keeps the test robust to timer
        noise on loaded CI hosts)."""
        from tpu_ddp.serve import calibrate_rate
        specs = make_workload(24, 1024, seed=5, prompt_len=(4, 13),
                              max_new=(4, 17))
        warm = _engine(model, params)
        for sp in specs[:2]:
            warm.submit(sp.prompt, sp.max_new_tokens)
        warm.run()
        probe = _engine(model, params)
        h = probe.submit(specs[0].prompt, specs[0].max_new_tokens)
        probe.run()
        slo = max(50.0, 10.0 * h.ttft_s * 1e3)
        cap = calibrate_rate(lambda: _engine(model, params), specs)
        cont = run_load(_engine(model, params), specs, 2.0 * cap,
                        seed=9, slo_ttft_ms=slo)
        stat = run_load(_engine(model, params, mode="static"), specs,
                        2.0 * cap, seed=9, slo_ttft_ms=slo)
        assert cont["goodput_tokens_per_sec"] \
            >= stat["goodput_tokens_per_sec"]


class TestDecodeCore:
    def test_rope_batched_positions_match_shared(self):
        # The (B, L) generalization that continuous batching needs:
        # each row at its own offset must equal the 1-D call at that
        # offset (the 1-D path is the pre-refactor program).
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(2, 5, 4, 8)), jnp.float32)
        p0, p1 = np.arange(3, 8), np.arange(11, 16)
        batched = rope(x, jnp.asarray(np.stack([p0, p1])))
        np.testing.assert_allclose(
            np.asarray(batched[0]),
            np.asarray(rope(x[:1], jnp.asarray(p0))[0]), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(batched[1]),
            np.asarray(rope(x[1:], jnp.asarray(p1))[0]), rtol=1e-6)

    def test_attend_cached_per_row_positions(self, model):
        from tpu_ddp.models.decode import attend_cached
        rng = np.random.default_rng(1)
        S = 16
        q = jnp.asarray(rng.normal(size=(2, 1, model.num_heads,
                                         model.head_dim)), jnp.float32)
        ck = jnp.asarray(rng.normal(size=(2, S, model.kv_heads,
                                          model.head_dim)), jnp.float32)
        cv = jnp.asarray(rng.normal(size=ck.shape), jnp.float32)
        pos = jnp.asarray([[3], [9]])
        got = attend_cached(model, q, ck, cv, pos)
        for b in range(2):
            want = attend_cached(model, q[b:b + 1], ck[b:b + 1],
                                 cv[b:b + 1], pos[b])
            np.testing.assert_allclose(np.asarray(got[b]),
                                       np.asarray(want[0]), rtol=1e-6)


class TestTrainServeRoundTrip:
    def _train(self, model, mesh_devices, tmp_path, **trainer_kw):
        from tpu_ddp.ops.optim import SGD
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch
        dp = len(mesh_devices)
        tr = LMTrainer(model, make_mesh(mesh_devices, dp=dp),
                       optimizer=SGD(learning_rate=0.1, momentum=0.9),
                       **trainer_kw)
        state = tr.init_state(seed=11)
        tokens = np.random.default_rng(2).integers(0, 1024,
                                                   size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        for _ in range(2):
            state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        return state

    def test_checkpoint_to_engine_logprob_parity(self, model, devices,
                                                 tmp_path):
        """The satellite the subsystem exists for: train a model,
        checkpoint through the canonical path, serve it — and the
        engine streams per-token logprobs equal to ``model.apply`` on
        the trained params, with tokens equal to ``generate()``'s."""
        state = self._train(model, devices[:1], tmp_path)
        eng = ServeEngine.from_checkpoint(model, str(tmp_path), **GEOM)
        prompt = _prompt(9, seed=80)
        req = eng.submit(prompt, 6)
        eng.run()
        trained = jax.tree.map(jnp.asarray, state.params)
        np.testing.assert_array_equal(
            np.asarray(req.tokens),
            _ref_greedy(model, trained, prompt, 6))
        np.testing.assert_allclose(
            np.asarray(req.logprobs),
            _ref_logprobs(model, trained, prompt, req.tokens),
            rtol=1e-4, atol=1e-4)

    # The under-budget and cross-strategy cells keep the restore path
    # fast; this adds only the tp-serving placement on top.
    @pytest.mark.slow
    def test_checkpoint_over_budget_serves_tensor_parallel(
            self, model, devices, tmp_path):
        """A checkpoint too big for one chip's param budget routes
        through shard_decode_params: params split Megatron-style over
        an mp mesh, both jitted steps run under GSPMD — and the tokens
        equal the dense engine's (column-parallel projections are
        communication-free; the row-parallel all-reduces change
        summation order, which greedy argmax absorbs)."""
        state = self._train(model, devices[:1], tmp_path)
        trained = jax.tree.map(jnp.asarray, state.params)
        nbytes = sum(x.nbytes for x in jax.tree.leaves(trained))
        eng = ServeEngine.from_checkpoint(
            model, str(tmp_path), param_budget_bytes=nbytes // 2,
            shard_devices=devices[:4], **GEOM)
        assert eng.mesh is not None
        wo = eng.params["blocks"][0]["wo"]
        assert not wo.sharding.is_fully_replicated
        prompt = _prompt(9, seed=82)
        req = eng.submit(prompt, 6)
        eng.run()
        np.testing.assert_array_equal(
            np.asarray(req.tokens),
            _ref_greedy(model, trained, prompt, 6))

    def test_checkpoint_under_budget_stays_dense(self, model, devices,
                                                 tmp_path):
        state = self._train(model, devices[:1], tmp_path)
        nbytes = sum(x.nbytes for x in
                     jax.tree.leaves(state.params))
        eng = ServeEngine.from_checkpoint(
            model, str(tmp_path), param_budget_bytes=2 * nbytes,
            **GEOM)
        assert eng.mesh is None   # round-12 single-chip path untouched

    def test_indivisible_tp_degree_refused(self, model, params,
                                           devices):
        from tpu_ddp.parallel.tensor_parallel import shard_decode_params
        with pytest.raises(ValueError, match="divisible"):
            shard_decode_params(model, params, devices[:3])

    def test_training_sharded_model_config_still_refused(self):
        # The pre-existing refusal: serving shards PARAMS of a dense
        # model config; a model CONFIGURED for training-time tp/sp/ep
        # layouts is still rejected loudly.
        from tpu_ddp.models.transformer import make_transformer
        tp_model = make_transformer("TransformerLM-tiny",
                                    max_seq_len=64, tp_axis="mp",
                                    tp_size=2)
        with pytest.raises(ValueError, match="dense"):
            ServeEngine(tp_model, {}, **GEOM)

    def test_cross_strategy_checkpoint_restores_dense(self, model,
                                                      devices,
                                                      tmp_path):
        """dense_params_from_checkpoint against a checkpoint written
        by a DIFFERENT strategy (dp=2 + ZeRO-1 sharded optimizer):
        the artifact is canonical, so the dense restore must equal the
        training-time params leaf-for-leaf and serve identically."""
        from tpu_ddp.models.decode import dense_params_from_checkpoint
        state = self._train(model, devices[:2], tmp_path,
                            opt_sharding="zero1")
        dense = dense_params_from_checkpoint(model, str(tmp_path))
        for a, b in zip(jax.tree.leaves(dense),
                        jax.tree.leaves(state.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        eng = ServeEngine(model, dense, **GEOM)
        prompt = _prompt(5, seed=81)
        req = eng.submit(prompt, 4)
        eng.run()
        np.testing.assert_array_equal(
            np.asarray(req.tokens),
            _ref_greedy(model, dense, prompt, 4))
