"""Ulysses (all-to-all) sequence parallelism.

Same decisive property as ring attention (tests/test_ring_attention.py):
the sp-sharded path computes EXACTLY the same function as the
single-device path, for values AND gradients, causal and not — Ulysses is
a re-sharding scheme, not an approximation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.parallel.mesh import SEQ_AXIS, make_mesh
from tpu_ddp.parallel.ring_attention import full_attention
from tpu_ddp.parallel.ulysses import ulysses_attention
from tpu_ddp.train.lm import LMTrainer, make_lm_batch


def _qkv(key, b=2, L=32, h=4, d=16):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, L, h, d)) for k in ks)


def _ulysses_on_mesh(mesh, sp, causal):
    def fn(q, k, v):
        return ulysses_attention(q, k, v, SEQ_AXIS, sp, causal=causal)
    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, SEQ_AXIS), P(None, SEQ_AXIS), P(None, SEQ_AXIS)),
        out_specs=P(None, SEQ_AXIS), check_vma=False))


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("sp", [2, 4])
    def test_matches_full_attention(self, devices, causal, sp):
        q, k, v = _qkv(jax.random.key(0))
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        got = _ulysses_on_mesh(mesh, sp, causal)(q, k, v)
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match(self, devices):
        q, k, v = _qkv(jax.random.key(1), L=16)
        sp = 4
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        uly = _ulysses_on_mesh(mesh, sp, True)

        def loss_uly(q, k, v):
            return jnp.sum(uly(q, k, v) ** 2)

        def loss_full(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

        g_u = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
        g_f = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_u, g_f):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_path_matches(self, devices, causal):
        """a2a -> Pallas flash kernel (interpret mode on CPU) -> a2a."""
        q, k, v = _qkv(jax.random.key(7))
        sp = 2
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)

        def fn(q, k, v):
            return ulysses_attention(q, k, v, SEQ_AXIS, sp, causal=causal,
                                     flash=True)
        got = jax.jit(jax.shard_map(
            fn, mesh=mesh,
            in_specs=(P(None, SEQ_AXIS),) * 3,
            out_specs=P(None, SEQ_AXIS), check_vma=False))(q, k, v)
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_head_divisibility_enforced(self, devices):
        # 4 heads cannot scatter over sp=8 slots.
        q, k, v = _qkv(jax.random.key(2), L=32, h=4)
        mesh = make_mesh(devices[:8], dp=1, sp=8)
        with pytest.raises(ValueError, match="num_heads % sp"):
            _ulysses_on_mesh(mesh, 8, False)(q, k, v)

    def test_requires_axis_size(self):
        q, k, v = _qkv(jax.random.key(3))
        with pytest.raises(ValueError, match="axis_size"):
            ulysses_attention(q, k, v, SEQ_AXIS, None)


class TestBlockwiseAttention:
    """The memory-bounded jnp path Ulysses uses locally: exact vs
    full_attention, including when L is not a block-size multiple."""

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("block_size", [8, 12, 64])
    def test_matches_full(self, causal, block_size):
        from tpu_ddp.parallel.ring_attention import blockwise_attention
        q, k, v = _qkv(jax.random.key(9), L=32)
        got = blockwise_attention(q, k, v, causal=causal,
                                  block_size=block_size)
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match(self):
        from tpu_ddp.parallel.ring_attention import blockwise_attention
        q, k, v = _qkv(jax.random.key(10), L=24)

        def loss(fn):
            return jax.grad(
                lambda q, k, v: jnp.sum(fn(q, k, v) ** 2),
                argnums=(0, 1, 2))(q, k, v)

        g_b = loss(lambda q, k, v: blockwise_attention(
            q, k, v, causal=True, block_size=8))
        g_f = loss(lambda q, k, v: full_attention(q, k, v, causal=True))
        for a, b in zip(g_b, g_f):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-5, atol=5e-5)


class TestUlyssesModel:
    def test_sp_sharded_matches_single_device(self, devices):
        """The whole MODEL under sp_mode='ulysses' (RoPE offsets + the two
        all_to_alls + loss path) equals the single-device function."""
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        params = model.init(jax.random.key(3))
        tokens = jax.random.randint(jax.random.key(4), (2, 32), 0, 1024)
        want = model.apply(params, tokens)

        sp = 4
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        sharded = model.with_sequence_parallel(SEQ_AXIS, sp, mode="ulysses")
        fn = jax.jit(jax.shard_map(
            sharded.apply, mesh=mesh,
            in_specs=(P(), P(None, SEQ_AXIS)),
            out_specs=P(None, SEQ_AXIS), check_vma=False))
        got = fn(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_mode_validation(self, devices):
        model = make_transformer("TransformerLM-tiny")
        with pytest.raises(ValueError, match="mode"):
            model.with_sequence_parallel(SEQ_AXIS, 2, mode="spiral")
        with pytest.raises(ValueError, match="ulysses"):
            # 4 heads, sp=8: ulysses impossible, ring would be fine.
            model.with_sequence_parallel(SEQ_AXIS, 8, mode="ulysses")
        # A typo'd mode fails at construction even on an sp=1 mesh where
        # it would be inert — not only after scaling sp up.
        with pytest.raises(ValueError, match="mode"):
            LMTrainer(model, make_mesh(devices[:2], dp=2),
                      sp_mode="ulyses")


class TestUlyssesTrainer:
    def test_train_step_matches_ring(self, devices):
        """One LMTrainer step under dp=2 x sp=4 produces the same params
        whether attention runs as ring or as Ulysses — they are two
        implementations of the same math."""
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 1024, size=(4, 33))
        inp, tgt = make_lm_batch(tokens)

        def one_step(sp_mode):
            mesh = make_mesh(devices[:8], dp=2, sp=4)
            tr = LMTrainer(model, mesh, sp_mode=sp_mode)
            state = tr.init_state(seed=11)
            x, y = tr.put_batch(inp, tgt)
            state, loss = tr.train_step(state, x, y)
            return jax.device_get(state.params), \
                float(np.mean(np.asarray(loss)))

        p_ring, l_ring = one_step("ring")
        p_uly, l_uly = one_step("ulysses")
        assert abs(l_ring - l_uly) < 1e-5
        for a, b in zip(jax.tree.leaves(p_ring), jax.tree.leaves(p_uly)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
