"""KV-cache generation: the cached decode computes exactly the same
function as running the full model over the growing sequence.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.generate import generate, init_cache, _forward_cached
from tpu_ddp.models.transformer import make_transformer


def _model(**kw):
    cfg = dict(max_seq_len=32, compute_dtype=jnp.float32)
    cfg.update(kw)
    return make_transformer("TransformerLM-tiny", **cfg)


def _prompt(b=2, L=8, seed=0):
    return np.random.default_rng(seed).integers(0, 1024, size=(b, L))


class TestCachedForward:
    def test_prefill_matches_apply(self):
        """Prefill logits at the last position == full apply's."""
        model = _model()
        params = model.init(jax.random.key(0))
        prompt = jnp.asarray(_prompt())
        caches = init_cache(model, 2, 16)
        logits, _ = _forward_cached(model, params, prompt, caches, 0)
        want = model.apply(params, prompt)[:, -1]
        np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.slow  # one recompile per grown length: ~20s on 1 core
    def test_incremental_matches_full_recompute(self):
        """Decoding one token with the cache == rerunning apply on the
        extended sequence, at every step."""
        model = _model()
        params = model.init(jax.random.key(1))
        prompt = jnp.asarray(_prompt(b=1, L=4, seed=2))
        caches = init_cache(model, 1, 12)
        logits, caches = _forward_cached(model, params, prompt, caches, 0)
        seq = np.asarray(prompt)
        for step in range(4):
            nxt = np.argmax(np.asarray(logits), axis=-1).astype(np.int32)
            seq = np.concatenate([seq, nxt[:, None]], axis=1)
            want = model.apply(params, jnp.asarray(seq))[:, -1]
            logits, caches = _forward_cached(
                model, params, jnp.asarray(nxt[:, None]), caches,
                seq.shape[1] - 1)
            np.testing.assert_allclose(
                np.asarray(logits), np.asarray(want),
                rtol=5e-5, atol=5e-5, err_msg=f"step {step}")


class TestGenerate:
    def test_greedy_matches_naive_decode(self):
        """generate() == argmax-decode by repeatedly calling apply."""
        model = _model()
        params = model.init(jax.random.key(3))
        prompt = _prompt(b=2, L=6, seed=4)
        got = np.asarray(generate(model, params, prompt,
                                  max_new_tokens=3))
        seq = prompt.copy()
        for _ in range(3):
            logits = model.apply(params, jnp.asarray(seq))[:, -1]
            nxt = np.argmax(np.asarray(logits), axis=-1)
            seq = np.concatenate([seq, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(got, seq[:, 6:])

    def test_single_token(self):
        model = _model()
        params = model.init(jax.random.key(5))
        out = generate(model, params, _prompt(), max_new_tokens=1)
        assert out.shape == (2, 1)

    def test_temperature_sampling_deterministic_per_key(self):
        model = _model()
        params = model.init(jax.random.key(6))
        prompt = _prompt(seed=7)
        a = generate(model, params, prompt, 4, temperature=1.0,
                     key=jax.random.key(42))
        b = generate(model, params, prompt, 4, temperature=1.0,
                     key=jax.random.key(42))
        c = generate(model, params, prompt, 4, temperature=1.0,
                     key=jax.random.key(7))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.any(np.asarray(a) != np.asarray(c))

    def test_validation(self):
        model = _model()
        params = model.init(jax.random.key(8))
        with pytest.raises(ValueError, match="max_seq_len"):
            generate(model, params, _prompt(L=30), max_new_tokens=10)
        with pytest.raises(ValueError, match="PRNG"):
            generate(model, params, _prompt(), 2, temperature=0.5)
        sharded = model.with_sequence_parallel("sp", 2)
        with pytest.raises(ValueError, match="dense"):
            generate(sharded, params, _prompt(), 2)
        with pytest.raises(ValueError, match="prompt_len"):
            generate(model, params, np.zeros((2, 0), np.int32), 2)
        # Dense MoE configs decode since round 21 (cached routed MLP,
        # parity pinned in tests/test_moe.py); only the ep-sharded
        # TRAINING layout still refuses, like sp/tp above.
        moe = make_transformer("TransformerLM-moe-tiny", max_seq_len=32,
                               compute_dtype=jnp.float32)
        with pytest.raises(ValueError, match="dense"):
            generate(moe.with_expert_parallel("ep", 2),
                     moe.init(jax.random.key(9)), _prompt(), 2)


class TestShardedCheckpointToGenerate:
    @pytest.mark.slow  # sharded trainer + dense-twin generate compiles;
    # the class's other drills already live in the slow tier
    def test_dp_sp_tp_checkpoint_generates_like_dense_twin(self, devices,
                                                           tmp_path):
        """The documented serving path: train under dp x sp x tp,
        checkpoint (canonical shapes), restore into a DENSE model,
        generate — the sampled continuation must equal a dense-trained
        twin's (models/generate.py's docstring claim, now tested)."""
        from tpu_ddp.ops.optim import SGD
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        tokens = np.random.default_rng(2).integers(0, 1024, size=(4, 33))
        opt = lambda: SGD(learning_rate=0.1, momentum=0.9,  # noqa: E731
                          weight_decay=1e-4)

        # Sharded training: dp=2 x sp=2 x tp=2 over 8 virtual devices.
        model = _model()
        sh_tr = LMTrainer(model, make_mesh(devices[:8], dp=2, sp=2, mp=2),
                          optimizer=opt())
        state = sh_tr.init_state(seed=11)
        x, y = sh_tr.put_batch(*make_lm_batch(tokens))
        for _ in range(2):
            state, _ = sh_tr.train_step(state, x, y)
        sh_tr.save_checkpoint(str(tmp_path), state)

        # Dense twin: same seed, same global batch, two steps.
        dense_tr = LMTrainer(model, make_mesh(devices[:1], dp=1),
                             optimizer=opt())
        dstate = dense_tr.init_state(seed=11)
        xd, yd = dense_tr.put_batch(*make_lm_batch(tokens))
        for _ in range(2):
            dstate, _ = dense_tr.train_step(dstate, xd, yd)

        # Restore the sharded checkpoint into the dense trainer and
        # sample greedily from both parameter sets.
        restored = dense_tr.restore_checkpoint(str(tmp_path))
        prompt = _prompt(b=2, L=6, seed=13)
        got = np.asarray(generate(model, restored.params, prompt,
                                  max_new_tokens=8))
        want = np.asarray(generate(model, dstate.params, prompt,
                                   max_new_tokens=8))
        np.testing.assert_array_equal(got, want)
