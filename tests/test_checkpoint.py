"""Checkpoint/resume subsystem (no reference equivalent — SURVEY.md §5
lists checkpointing as absent upstream; it is native to this framework)."""

import os

import jax
import numpy as np
import pytest

from tpu_ddp.models import get_model
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils import checkpoint as ckpt
from tpu_ddp.utils.config import TrainConfig


def _tree(seed=0):
    k = jax.random.split(jax.random.key(seed), 3)
    return {"a": jax.random.normal(k[0], (4, 3)),
            "b": {"c": jax.random.normal(k[1], (7,)),
                  "d": jax.random.normal(k[2], (2, 2, 2))}}


class TestCheckpointCore:
    def test_roundtrip_bit_exact(self, tmp_path):
        tree = _tree()
        ckpt.save_checkpoint(str(tmp_path), tree, step=5)
        restored, step = ckpt.restore_checkpoint(str(tmp_path), tree)
        assert step == 5
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), tree, restored)

    def test_latest_and_explicit_step(self, tmp_path):
        t1, t2 = _tree(1), _tree(2)
        ckpt.save_checkpoint(str(tmp_path), t1, step=1)
        ckpt.save_checkpoint(str(tmp_path), t2, step=2)
        assert ckpt.all_steps(str(tmp_path)) == [1, 2]
        r, s = ckpt.restore_checkpoint(str(tmp_path), t1)
        assert s == 2
        np.testing.assert_array_equal(np.asarray(r["a"]), np.asarray(t2["a"]))
        r1, s1 = ckpt.restore_checkpoint(str(tmp_path), t1, step=1)
        assert s1 == 1
        np.testing.assert_array_equal(np.asarray(r1["a"]),
                                      np.asarray(t1["a"]))

    def test_keep_last_prunes(self, tmp_path):
        for s in range(5):
            ckpt.save_checkpoint(str(tmp_path), _tree(), step=s,
                                 keep_last=2)
        assert ckpt.all_steps(str(tmp_path)) == [3, 4]

    def test_partial_write_invisible(self, tmp_path):
        os.makedirs(tmp_path / ".tmp-abc")
        (tmp_path / ".tmp-abc" / "arrays.npz").write_bytes(b"junk")
        os.makedirs(tmp_path / "step_00000009")  # no manifest => incomplete
        assert ckpt.all_steps(str(tmp_path)) == []
        with pytest.raises(FileNotFoundError):
            ckpt.restore_checkpoint(str(tmp_path), _tree())

    def test_structure_mismatch_raises(self, tmp_path):
        ckpt.save_checkpoint(str(tmp_path), _tree(), step=0)
        bad = {"a": np.zeros((4, 3)), "b": {"c": np.zeros((7,))}}
        with pytest.raises(ValueError, match="structures differ"):
            ckpt.restore_checkpoint(str(tmp_path), bad)
        bad_shape = _tree()
        bad_shape["a"] = np.zeros((5, 3))
        with pytest.raises(ValueError, match="shape"):
            ckpt.restore_checkpoint(str(tmp_path), bad_shape)


class TestAsyncWriter:
    def test_roundtrip_after_wait(self, tmp_path):
        w = ckpt.AsyncCheckpointWriter()
        tree = _tree()
        path = w.submit(str(tmp_path), tree, step=3)
        w.wait()
        assert os.path.isdir(path)
        restored, step = ckpt.restore_checkpoint(str(tmp_path), tree)
        assert step == 3
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), tree, restored)

    def test_submits_are_ordered_and_keep_last_applies(self, tmp_path):
        w = ckpt.AsyncCheckpointWriter()
        for s in (1, 2, 3):
            w.submit(str(tmp_path), _tree(seed=s), step=s, keep_last=2)
        w.wait()
        assert ckpt.all_steps(str(tmp_path)) == [2, 3]

    def test_snapshot_is_consistent(self, tmp_path):
        """The host snapshot happens at submit time: mutating the source
        arrays afterwards must not leak into the written checkpoint."""
        tree = {"a": np.zeros((1000, 100), np.float32)}
        w = ckpt.AsyncCheckpointWriter()
        w.submit(str(tmp_path), {"a": jax.numpy.asarray(tree["a"])},
                 step=1)
        tree["a"][:] = 7.0  # the device array snapshot is independent
        w.wait()
        restored, _ = ckpt.restore_checkpoint(str(tmp_path), tree)
        assert float(np.abs(restored["a"]).max()) == 0.0

    def test_write_failure_surfaces(self, tmp_path):
        w = ckpt.AsyncCheckpointWriter()
        target = tmp_path / "f"
        target.write_text("not a directory")
        w.submit(str(target), _tree(), step=1)  # mkdir over a file fails
        with pytest.raises(RuntimeError, match="background checkpoint"):
            w.wait()
        # The error is consumed: the writer is reusable afterwards.
        w.submit(str(tmp_path), _tree(), step=2)
        w.wait()
        assert ckpt.all_steps(str(tmp_path)) == [2]

    def test_write_failure_surfaces_from_next_submit(self, tmp_path):
        """The contract's other half: a train loop that only ever calls
        submit() (never wait()) still hears about a dead writer at the
        NEXT submit — the failure cannot be silently ignored."""
        w = ckpt.AsyncCheckpointWriter()
        target = tmp_path / "f"
        target.write_text("not a directory")
        w.submit(str(target), _tree(), step=1)
        with pytest.raises(RuntimeError, match="background checkpoint"):
            w.submit(str(tmp_path), _tree(), step=2)
        # The failed submit consumed the error and did NOT start a new
        # write; the writer is clean for reuse.
        w.submit(str(tmp_path), _tree(), step=3)
        w.wait()
        assert ckpt.all_steps(str(tmp_path)) == [3]

    def test_write_failure_surfaces_from_atexit_drain(self, tmp_path):
        """A failed in-flight write with NO later submit/wait must still
        surface at the registered atexit drain — a clean process exit
        cannot swallow the loss of the final checkpoint."""
        w = ckpt.AsyncCheckpointWriter()
        target = tmp_path / "f"
        target.write_text("not a directory")
        w.submit(str(target), _tree(), step=1)
        with pytest.raises(RuntimeError, match="background checkpoint"):
            ckpt.AsyncCheckpointWriter._drain_all()
        # Consumed: a second drain (the real atexit would run once) is
        # clean, as is later reuse.
        ckpt.AsyncCheckpointWriter._drain_all()
        w.submit(str(tmp_path), _tree(), step=2)
        w.wait()

    def test_drain_all_drains_every_writer_despite_failure(self,
                                                           tmp_path):
        """One failed writer must not abandon other writers' in-flight
        checkpoints: the drain completes them all, THEN re-raises."""
        bad = ckpt.AsyncCheckpointWriter()
        good = ckpt.AsyncCheckpointWriter()
        target = tmp_path / "f"
        target.write_text("not a directory")
        bad.submit(str(target), _tree(), step=1)
        good_dir = tmp_path / "ok"
        good.submit(str(good_dir), _tree(), step=5)
        with pytest.raises(RuntimeError, match="background checkpoint"):
            ckpt.AsyncCheckpointWriter._drain_all()
        assert ckpt.all_steps(str(good_dir)) == [5]

    def test_trainer_background_save(self, tmp_path, devices):
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jax.numpy.float32)
        tr = LMTrainer(model, make_mesh(devices[:2], dp=2))
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(0).integers(0, 1024, size=(2, 17))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        # Snapshot BEFORE the next step: train_step donates its input
        # state's buffers, so `state.params` is dead after stepping on it.
        want = jax.tree.map(lambda x: np.array(x, copy=True),
                            jax.device_get(state.params))
        saved_step = state.step
        tr.save_checkpoint(str(tmp_path), state, background=True)
        state2, _ = tr.train_step(state, x, y)  # train while it writes
        tr.wait_for_checkpoints()
        restored = tr.restore_checkpoint(str(tmp_path))
        assert restored.step == saved_step
        for a, b in zip(jax.tree.leaves(want),
                        jax.tree.leaves(jax.device_get(restored.params))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestTrainerResume:
    def _batch(self, n=8):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
        y = (np.arange(n) % 10).astype(np.int32)
        return x, y

    @pytest.mark.slow  # three trainer steps + restore compile; roundtrip
    # layout checks stay fast above
    def test_resume_continues_identically(self, tmp_path, devices):
        """save -> restore -> one step == uninterrupted two steps."""
        import jax.numpy as jnp

        from tpu_ddp.parallel.mesh import make_mesh

        cfg = TrainConfig(global_batch_size=8)
        model = get_model("VGG11", compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4])
        x, y = self._batch()

        tr = Trainer(model, cfg, strategy="fused", mesh=mesh)
        state = tr.init_state()
        xb, yb, wb = tr.put_batch(x, y)
        state, _ = tr.train_step(state, xb, yb, wb)
        tr.save_checkpoint(str(tmp_path), state)
        state, _ = tr.train_step(state, xb, yb, wb)  # uninterrupted path

        tr2 = Trainer(model, cfg, strategy="fused", mesh=mesh)
        state2 = tr2.restore_checkpoint(str(tmp_path))
        assert state2.step == 1
        xb2, yb2, wb2 = tr2.put_batch(x, y)
        state2, _ = tr2.train_step(state2, xb2, yb2, wb2)

        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            state.params, state2.params)
        assert state2.step == state.step == 2


class TestLMCheckpoint:
    """Checkpoint/resume for the LM trainers, including sharded layouts
    (tp-split leaves, pp-stacked blocks) that must gather on save and
    re-shard on restore."""

    def _tokens(self, b=4, L=17, seed=9):
        rng = np.random.default_rng(seed)
        return rng.integers(0, 1024, size=(b, L))

    # Core roundtrips and the fsdp/zero restore tests keep checkpoint
    # coverage fast; the tp layout adds only placement on top.
    @pytest.mark.slow
    def test_lm_trainer_roundtrip_tp(self, tmp_path, devices):
        import jax.numpy as jnp

        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=2, sp=1, mp=2)
        tr = LMTrainer(model, mesh)
        state = tr.init_state(seed=1)
        x, y = tr.put_batch(*make_lm_batch(self._tokens()))
        state, _ = tr.train_step(state, x, y)
        path = tr.save_checkpoint(str(tmp_path), state)
        assert path is not None
        state, _ = tr.train_step(state, x, y)  # uninterrupted path

        tr2 = LMTrainer(model, mesh)
        state2 = tr2.restore_checkpoint(str(tmp_path))
        assert state2.step == 1
        x2, y2 = tr2.put_batch(*make_lm_batch(self._tokens()))
        state2, _ = tr2.train_step(state2, x2, y2)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6),
            jax.device_get(state.params), jax.device_get(state2.params))

    @pytest.mark.slow  # pp trainer compile just for a save/restore pass;
    # tp and dense roundtrips stay in the default tier
    def test_pipeline_trainer_roundtrip(self, tmp_path, devices):
        import jax.numpy as jnp

        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import PipelineLMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
        tr = PipelineLMTrainer(model, mesh, num_micro=2)
        state = tr.init_state(seed=2)
        x, y = tr.put_batch(*make_lm_batch(self._tokens()))
        state, loss = tr.train_step(state, x, y)
        path = tr.save_checkpoint(str(tmp_path), state)
        assert path is not None

        tr2 = PipelineLMTrainer(model, mesh, num_micro=2)
        state2 = tr2.restore_checkpoint(str(tmp_path))
        assert state2.step == 1
        # Stacked block leaves restored into their pp sharding.
        leaf = state2.params["blocks"]["wqkv"]
        assert leaf.sharding.spec[0] == "pp"
        s1, l1 = tr.train_step(state, x, y)
        s2, l2 = tr2.train_step(state2, x, y)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2),
                                   rtol=1e-6)
