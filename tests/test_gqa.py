"""Grouped-query attention (tpu_ddp/models/transformer.py num_kv_heads).

Decisive properties: (i) the KV projection and decode cache shrink to
num_kv_heads while logits stay causal and well-formed; (ii) GQA with
group size 1 (kv == heads via expand) changes nothing; (iii) GQA
composes with the sharded paths (tp, sp ring, sp ulysses) computing the
same function as single-device; (iv) decode with the KV-width cache
matches the full forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.parallel.mesh import MODEL_AXIS, SEQ_AXIS, make_mesh


def _gqa(kv=2, **kw):
    kw.setdefault("max_seq_len", 32)
    return make_transformer("TransformerLM-tiny",
                            compute_dtype=jnp.float32, num_kv_heads=kv,
                            **kw)


class TestParams:
    def test_layout_and_shapes(self):
        model = _gqa(kv=2)  # 4 q heads, 2 kv heads
        params = model.init(jax.random.key(0))
        blk = params["blocks"][0]
        assert "wqkv" not in blk
        assert blk["wq"].shape == (128, 4, 32)
        assert blk["wkv"].shape == (128, 2, 2, 32)

    def test_mha_layout_unchanged(self):
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        blk = model.init(jax.random.key(0))["blocks"][0]
        assert "wq" not in blk and blk["wqkv"].shape == (128, 3, 4, 32)

    def test_divisibility_enforced(self):
        with pytest.raises(ValueError, match="num_kv_heads"):
            _gqa(kv=3)
        with pytest.raises(ValueError, match="num_kv_heads"):
            _gqa(kv=0)

    def test_tp_requires_kv_divisibility(self):
        with pytest.raises(ValueError, match="num_kv_heads"):
            _gqa(kv=2).with_tensor_parallel(MODEL_AXIS, 4)


class TestForward:
    def test_causal_property(self):
        model = _gqa(kv=2, max_seq_len=16)
        params = model.init(jax.random.key(1))
        t = jax.random.randint(jax.random.key(2), (1, 16), 0, 1024)
        l1 = model.apply(params, t)
        t2 = t.at[0, 10].set((t[0, 10] + 7) % 1024)
        l2 = model.apply(params, t2)
        np.testing.assert_allclose(np.asarray(l1[:, :10]),
                                   np.asarray(l2[:, :10]),
                                   rtol=1e-5, atol=1e-5)
        assert l1.shape == (1, 16, model.vocab_size)

    def test_mqa_extreme(self):
        """num_kv_heads=1 (multi-query) runs and differs from MHA."""
        model = _gqa(kv=1, max_seq_len=16)
        params = model.init(jax.random.key(3))
        t = jax.random.randint(jax.random.key(4), (2, 16), 0, 1024)
        logits = model.apply(params, t)
        assert np.isfinite(np.asarray(logits)).all()

    def test_tp_sharded_matches_single_device(self, devices):
        model = _gqa(kv=2)
        params = model.init(jax.random.key(5))
        tokens = jax.random.randint(jax.random.key(6), (2, 32), 0, 1024)
        want = model.apply(params, tokens)

        tp = 2
        mesh = make_mesh(devices[:tp], dp=1, mp=tp)
        sharded = model.with_tensor_parallel(MODEL_AXIS, tp)
        specs = sharded.param_specs()
        fn = jax.jit(jax.shard_map(
            sharded.apply, mesh=mesh,
            in_specs=(specs, P()), out_specs=P(), check_vma=False))
        got = fn(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    @pytest.mark.parametrize("mode", ["ring", "ulysses"])
    def test_sp_sharded_matches_single_device(self, devices, mode):
        model = _gqa(kv=2)
        params = model.init(jax.random.key(7))
        tokens = jax.random.randint(jax.random.key(8), (2, 32), 0, 1024)
        want = model.apply(params, tokens)

        sp = 4
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        sharded = model.with_sequence_parallel(SEQ_AXIS, sp, mode=mode)
        fn = jax.jit(jax.shard_map(
            sharded.apply, mesh=mesh,
            in_specs=(P(), P(None, SEQ_AXIS)),
            out_specs=P(None, SEQ_AXIS), check_vma=False))
        got = fn(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


class TestGroupedKernels:
    """attend()'s GQA paths contract KV-width k/v without expansion;
    every path must equal the materialized-expansion reference."""

    def _qkv(self, key, L=32, h=4, kv=2, d=16):
        ks = jax.random.split(key, 3)
        q = jax.random.normal(ks[0], (2, L, h, d))
        k = jax.random.normal(ks[1], (2, L, kv, d))
        v = jax.random.normal(ks[2], (2, L, kv, d))
        return q, k, v

    def _expanded(self, q, k, v, causal):
        from tpu_ddp.parallel.ring_attention import (full_attention,
                                                     repeat_kv_heads)
        k, v = repeat_kv_heads(k, v, q.shape[2] // k.shape[2])
        return full_attention(q, k, v, causal=causal)

    @pytest.mark.parametrize("causal", [False, True])
    def test_full_grouped(self, causal):
        from tpu_ddp.parallel.ring_attention import full_attention
        q, k, v = self._qkv(jax.random.key(20))
        np.testing.assert_allclose(
            np.asarray(full_attention(q, k, v, causal=causal)),
            np.asarray(self._expanded(q, k, v, causal)),
            rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_blockwise_grouped(self, causal):
        from tpu_ddp.parallel.ring_attention import blockwise_attention
        q, k, v = self._qkv(jax.random.key(21))
        got = blockwise_attention(q, k, v, causal=causal, block_size=8)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(self._expanded(q, k, v, causal)),
            rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("kv,sp", [(2, 2), (2, 4), (1, 4)])
    def test_ring_grouped(self, devices, kv, sp):
        from tpu_ddp.parallel.ring_attention import ring_attention
        q, k, v = self._qkv(jax.random.key(22), kv=kv)
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        fn = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, SEQ_AXIS, sp,
                                           causal=True),
            mesh=mesh, in_specs=(P(None, SEQ_AXIS),) * 3,
            out_specs=P(None, SEQ_AXIS), check_vma=False))
        np.testing.assert_allclose(
            np.asarray(fn(q, k, v)),
            np.asarray(self._expanded(q, k, v, True)),
            rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("kv,sp", [(2, 2), (2, 4), (1, 2)])
    def test_ulysses_grouped(self, devices, kv, sp):
        """kv % sp == 0 scatters grouped K/V; kv % sp != 0 falls back to
        pre-collective expansion — both must be exact."""
        from tpu_ddp.parallel.ulysses import ulysses_attention
        q, k, v = self._qkv(jax.random.key(23), kv=kv)
        mesh = make_mesh(devices[:sp], dp=1, sp=sp)
        fn = jax.jit(jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, SEQ_AXIS, sp,
                                              causal=True),
            mesh=mesh, in_specs=(P(None, SEQ_AXIS),) * 3,
            out_specs=P(None, SEQ_AXIS), check_vma=False))
        np.testing.assert_allclose(
            np.asarray(fn(q, k, v)),
            np.asarray(self._expanded(q, k, v, True)),
            rtol=2e-5, atol=2e-5)

    def test_flash_gqa_model(self):
        """use_flash + GQA at sp=1: the kernel sees expanded K/V, logits
        match the non-flash model."""
        base = _gqa(kv=2, max_seq_len=16)
        flash = _gqa(kv=2, max_seq_len=16, use_flash=True)
        params = base.init(jax.random.key(24))
        t = jax.random.randint(jax.random.key(25), (2, 16), 0, 1024)
        np.testing.assert_allclose(np.asarray(flash.apply(params, t)),
                                   np.asarray(base.apply(params, t)),
                                   rtol=2e-4, atol=2e-4)


class TestDecode:
    def test_cache_is_kv_width(self):
        from tpu_ddp.models.generate import init_cache
        model = _gqa(kv=2)
        caches = init_cache(model, batch=2, max_len=16)
        ck, cv = caches[0]
        assert ck.shape == (2, 16, 2, 32)  # KV heads, not 4 Q heads

    def test_cached_decode_matches_full_forward(self):
        """Greedy next-token from the KV-cache decode path equals the
        argmax of the full (uncached) forward at every step."""
        from tpu_ddp.models.generate import generate
        model = _gqa(kv=2, max_seq_len=32)
        params = model.init(jax.random.key(9))
        prompt = jax.random.randint(jax.random.key(10), (2, 5), 0, 1024)
        out = generate(model, params, prompt, max_new_tokens=3)
        assert out.shape == (2, 3)  # generated continuation only
        # Re-derive each generated token from full forwards (each grown
        # length is a fresh compile on the 1-core host: keep it short).
        seq = np.asarray(prompt)
        for i in range(3):
            logits = model.apply(params, jnp.asarray(seq))
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
            assert (nxt == np.asarray(out)[:, i]).all()
            seq = np.concatenate([seq, nxt[:, None]], axis=1)
