"""DiLoCo outer loop: the cross-datacenter rung (DESIGN.md §29).

The pins the module docstrings promise, in test form:

- ``diloco_h=0`` is INERT — the existing sync path traces
  byte-for-byte as if ``train/outer.py`` did not exist;
- ``H=1, outer_lr=1, zero momentum, wire=none`` matches plain synced
  training bitwise (the identity outer optimizer adopts ``mean_end``
  structurally, the lossless wire ships full pushes that decode
  bitwise);
- the int8 outer wire's error-feedback residual lifecycle: carried
  across rounds, reset WITH a warning on a group-count change, and
  untouched when the StepGuard skip protocol fires (flags are
  collected BEFORE any codec encodes);
- elastic membership: a lost group reweights the outer mean, a
  rejoiner boots digest-equal at the current outer version;
- the chaos grammar (``group-loss@N:group=G``) parses, validates, and
  one-shots via the sentinel like every other fault kind.
"""

import dataclasses
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.optim import SGD
from tpu_ddp.parallel.diloco import (UpdateEdge, decode_update,
                                     lower_outer_step, mean_end_leaves,
                                     outer_program)
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.parallel.overlap import BucketPlan
from tpu_ddp.train.lm import LMTrainer, make_lm_batch
from tpu_ddp.train.outer import DilocoGroup, OuterLoop

_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        _MODEL = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                  compute_dtype=jnp.float32)
    return _MODEL


def _make_group(devices, gid, lo, hi):
    mesh = make_mesh(devices[lo:hi], dp=hi - lo)
    trainer = LMTrainer(_model(), mesh,
                        optimizer=SGD(learning_rate=0.1, momentum=0.9))
    return DilocoGroup(gid, trainer, trainer.init_state(seed=3))


def _batch_fn():
    rng = np.random.default_rng(0)
    batches = [rng.integers(0, 128, size=(4, 17)) for _ in range(64)]
    cursor = {}

    def next_batch(group):
        i = cursor.get(group.gid, 0)
        cursor[group.gid] = i + 1
        return group.trainer.put_batch(
            *make_lm_batch(batches[i % len(batches)]))

    return next_batch


# ---------------------------------------------------------------------------
# Knob surfaces (construction validation + env junk rejection).
# ---------------------------------------------------------------------------


def test_outer_loop_validates_knobs():
    with pytest.raises(ValueError, match="diloco_h"):
        OuterLoop([], diloco_h=-1)
    with pytest.raises(ValueError, match="outer_lr"):
        OuterLoop([], diloco_h=0, outer_lr=0.0)
    with pytest.raises(ValueError, match="outer_momentum"):
        OuterLoop([], diloco_h=0, outer_momentum=1.0)
    with pytest.raises(ValueError, match="outer_wire"):
        OuterLoop([], diloco_h=0, outer_wire="zstd")
    with pytest.raises(ValueError, match="at least one group"):
        OuterLoop([], diloco_h=4)


def test_env_junk_rejected(monkeypatch):
    from tpu_ddp.utils.config import TrainConfig
    for env, junk in [("TPU_DDP_DILOCO_H", "many"),
                      ("TPU_DDP_DILOCO_H", "-2"),
                      ("TPU_DDP_DILOCO_OUTER_LR", "fast"),
                      ("TPU_DDP_DILOCO_OUTER_LR", "0"),
                      ("TPU_DDP_DILOCO_OUTER_LR", "nan"),
                      ("TPU_DDP_DILOCO_OUTER_MOMENTUM", "heavy"),
                      ("TPU_DDP_DILOCO_OUTER_MOMENTUM", "1.0"),
                      ("TPU_DDP_DILOCO_OUTER_WIRE", "zstd")]:
        monkeypatch.setenv(env, junk)
        with pytest.raises(ValueError, match=env):
            TrainConfig()
        monkeypatch.delenv(env)
    monkeypatch.setenv("TPU_DDP_DILOCO_H", "8")
    monkeypatch.setenv("TPU_DDP_DILOCO_OUTER_LR", "0.4")
    monkeypatch.setenv("TPU_DDP_DILOCO_OUTER_MOMENTUM", "0.5")
    monkeypatch.setenv("TPU_DDP_DILOCO_OUTER_WIRE", "int8")
    cfg = TrainConfig()
    assert (cfg.diloco_h, cfg.outer_lr, cfg.outer_momentum,
            cfg.outer_wire) == (8, 0.4, 0.5, "int8")


# ---------------------------------------------------------------------------
# The jitted outer program (in-graph guard + identity shortcut).
# ---------------------------------------------------------------------------


def test_outer_program_guard_is_exact_noop():
    start = (np.full((4,), 2.0, np.float32),
             np.full((2, 3), -1.0, np.float32))
    momentum = tuple(np.full(s.shape, 0.25, np.float32) for s in start)
    poisoned = (np.full((4,), np.nan, np.float32),
                np.full((2, 3), -1.1, np.float32))
    new, m_out, bad = outer_program(0.7, 0.9)(
        tuple(np.copy(s) for s in start), poisoned,
        tuple(np.copy(m) for m in momentum))
    assert bool(np.asarray(bad))
    # select_update keeps the OLD params and momentum bitwise on EVERY
    # leaf — the non-finite round is an exact in-graph no-op.
    for got, want in zip(new, start):
        assert np.asarray(got).tobytes() == want.tobytes()
    for got, want in zip(m_out, momentum):
        assert np.asarray(got).tobytes() == want.tobytes()


def test_outer_program_identity_adopts_mean_end_bitwise():
    start = (np.linspace(0, 1, 8).astype(np.float32),)
    end = (np.linspace(3, 7, 8).astype(np.float32),)
    new, m_out, bad = outer_program(1.0, 0.0)(
        (np.copy(start[0]),), (np.copy(end[0]),),
        (np.zeros((8,), np.float32),))
    assert not bool(np.asarray(bad))
    # lr=1 + mu=0 adopts mean_end STRUCTURALLY (no delta arithmetic),
    # so the result is bitwise the input — not just close.
    assert np.asarray(new[0]).tobytes() == end[0].tobytes()


def test_outer_program_nesterov_math():
    s, e = np.float32(1.0), np.float32(0.6)
    m0 = np.float32(0.2)
    lr, mu = 0.5, 0.9
    new, m_out, _ = outer_program(lr, mu)(
        (np.full((2,), s),), (np.full((2,), e),), (np.full((2,), m0),))
    g = s - e
    m1 = mu * m0 + g
    want = s - lr * (g + mu * m1)
    np.testing.assert_allclose(np.asarray(new[0]),
                               np.full((2,), want), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(m_out[0]),
                               np.full((2,), m1), rtol=1e-6)


def test_mean_end_reweights_by_live_count():
    a = [np.full((3,), 2.0, np.float32)]
    b = [np.full((3,), 4.0, np.float32)]
    np.testing.assert_array_equal(mean_end_leaves([a, b])[0],
                                  np.full((3,), 3.0, np.float32))
    # A lost group is simply absent from the divisor.
    np.testing.assert_array_equal(mean_end_leaves([a])[0], a[0])
    with pytest.raises(ValueError, match="zero groups"):
        mean_end_leaves([])


# ---------------------------------------------------------------------------
# The h=0 inert pin: the sync path cannot tell this module exists.
# ---------------------------------------------------------------------------


def test_h0_inert_traces_sync_path_byte_for_byte(devices):
    g = _make_group(devices, 0, 0, 2)
    x, y = g.trainer.put_batch(*make_lm_batch(
        np.zeros((4, 17), np.int64)))
    before = g.trainer.lower_train_step(g.state, x, y).as_text()
    loop = OuterLoop([g], diloco_h=0, outer_wire="int8")
    assert not loop.active and loop.down is None and loop.plan is None
    with pytest.raises(RuntimeError, match="inert"):
        loop.round(_batch_fn())
    assert g.sub is None and g.up_pub is None
    # The exact HLO the sync path lowers, with the inert loop
    # constructed: byte-for-byte unchanged.
    after = g.trainer.lower_train_step(g.state, x, y).as_text()
    assert before == after


# ---------------------------------------------------------------------------
# The bitwise identity pin: H=1 / lr=1 / mu=0 / wire=none == plain sync.
# ---------------------------------------------------------------------------


def test_identity_outer_matches_plain_training_bitwise(devices):
    T = 3
    g = _make_group(devices, 0, 0, 2)
    loop = OuterLoop([g], diloco_h=1, outer_lr=1.0, outer_momentum=0.0,
                     outer_wire="none")
    nb = _batch_fn()
    for _ in range(T):
        st = loop.round(nb)
        assert not st["skipped"]

    plain = _make_group(devices, 0, 0, 2)
    nb2 = _batch_fn()
    for _ in range(T):
        plain.run_inner(1, nb2)

    la = jax.tree.leaves(g.host_params())
    lb = jax.tree.leaves(plain.host_params())
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        assert a.dtype == b.dtype and a.tobytes() == b.tobytes()


# ---------------------------------------------------------------------------
# int8 EF residual lifecycle + skip protocol + elastic membership.
# ---------------------------------------------------------------------------


def _residual_bytes(group):
    return [np.asarray(c._residual).tobytes()
            for c in group.up_pub._codecs
            if getattr(c, "_residual", None) is not None]


def test_int8_residual_lifecycle_skip_and_membership(devices):
    g0 = _make_group(devices, 0, 0, 2)
    g1 = _make_group(devices, 1, 2, 4)
    loop = OuterLoop([g0, g1], diloco_h=1, outer_lr=0.7,
                     outer_momentum=0.9, outer_wire="int8")
    nb = _batch_fn()

    st = loop.round(nb)
    assert not st["skipped"] and st["groups"] == [0, 1]
    res1 = _residual_bytes(g0)
    # int8 quantization of a real pseudo-gradient leaves a residual.
    assert res1 and any(np.frombuffer(r, np.float32).any()
                        for r in res1)

    st = loop.round(nb)
    assert not st["skipped"]
    res2 = _residual_bytes(g0)
    # Carried ACROSS rounds: round 2 encoded residual+delta and left a
    # new remainder — the state persists, it is not reset per round.
    assert len(res2) == len(res1) and res2 != res1
    assert loop.digest_equal(g0) and loop.digest_equal(g1)

    # --- skip protocol: flags are collected BEFORE any publish -------
    before = [np.copy(x) for x in loop.global_leaves]
    mom_before = [np.copy(m) for m in loop.momentum]
    bad = jax.tree.map(
        lambda x: (x * np.float32("nan")).astype(x.dtype),
        g1.state.params)
    g1.state = dataclasses.replace(g1.state, params=bad)
    g1.last_loss = float("nan")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        st = loop.round(nb)
    assert st["skipped"] and st["bad_groups"] == [1]
    assert any("skipped" in str(x.message) for x in w)
    assert any("optimizer state reset" in str(x.message) for x in w)
    # Nothing was published: EF residuals, global params and outer
    # momentum are all bitwise untouched; every group is back at the
    # round's agreed start.
    assert _residual_bytes(g0) == res2
    for a, b in zip(before, loop.global_leaves):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    for a, b in zip(mom_before, loop.momentum):
        assert a.tobytes() == np.asarray(b).tobytes()
    assert loop.digest_equal(g0) and loop.digest_equal(g1)
    st = loop.round(nb)
    assert not st["skipped"], "skip protocol must recover next round"

    # --- membership change: residuals reset WITH a warning -----------
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        loop.remove_group(1, reason="lost heartbeat")
    msgs = [str(x.message) for x in w]
    assert any("reweight" in m for m in msgs)
    assert any("error-feedback residuals reset" in m for m in msgs)
    assert not _residual_bytes(g0), "survivor residuals must reset"
    st = loop.round(nb)
    assert not st["skipped"] and st["groups"] == [0]

    rejoiner = loop.removed[1]
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        loop.add_group(rejoiner)
    msgs = [str(x.message) for x in w]
    assert any("joined at outer version" in m for m in msgs)
    assert any("error-feedback residuals reset" in m for m in msgs)
    # Rejoiner boots digest-equal at the CURRENT outer version.
    assert loop.digest_equal(rejoiner)
    assert rejoiner.sub.applied_version == loop.down.version
    st = loop.round(nb)
    assert not st["skipped"] and st["groups"] == [0, 1]


@pytest.mark.slow
@pytest.mark.parametrize("wire", ["bf16", "sparse"])
def test_other_wires_converge_digest_equal(devices, wire):
    g0 = _make_group(devices, 0, 0, 2)
    g1 = _make_group(devices, 1, 2, 4)
    loop = OuterLoop([g0, g1], diloco_h=2, outer_lr=0.7,
                     outer_momentum=0.9, outer_wire=wire)
    nb = _batch_fn()
    for _ in range(2):
        st = loop.round(nb)
        assert not st["skipped"]
    assert np.isfinite(st["loss"])
    assert loop.digest_equal(g0) and loop.digest_equal(g1)
    assert loop.cross_group_bytes() > 0


# ---------------------------------------------------------------------------
# The DCN hop + host-side decode verification.
# ---------------------------------------------------------------------------


def _host_tree(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": rng.standard_normal((64, 8)).astype(np.float32),
            "b": rng.standard_normal((8,)).astype(np.float32)}


def test_update_edge_ships_weight_updates_framed():
    from tpu_ddp.publish.publisher import Publisher
    tree = _host_tree()
    pub = Publisher(publish_every=1, wire="int8",
                    max_staleness_steps=0, bucket_mb=0.25)
    update = pub.publish(params=tree, step=0)
    edge = UpdateEdge()
    edge.send(update)
    got = edge.recv()
    assert got.kind == update.kind and got.version == update.version
    assert got.digests == update.digests
    import pickle
    assert pickle.dumps(got.wires) == pickle.dumps(update.wires)
    st = edge.stats()
    assert st["messages"] == 1 and st["wire_bytes"] > update.nbytes


def test_decode_update_rejects_layout_and_digest_mismatch():
    from tpu_ddp.publish.publisher import Publisher
    tree = _host_tree()
    pub = Publisher(publish_every=1, wire="bf16",
                    max_staleness_steps=0, bucket_mb=0.25)
    full = pub.publish(params=tree, step=0)
    plan = BucketPlan(pub.reconstruction(), 0.25)
    leaves, recon = decode_update(full, plan)
    for a, b in zip(leaves, jax.tree.leaves(pub.reconstruction())):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()

    wrong_plan = BucketPlan({"w": np.zeros((4, 4), np.float32)}, 0.25)
    with pytest.raises(ValueError, match="layout"):
        decode_update(full, wrong_plan)

    moved = jax.tree.map(lambda x: x + 0.125, tree)
    delta = pub.publish(params=moved, step=1)
    assert delta.kind == "delta"
    with pytest.raises(ValueError, match="last_leaves"):
        decode_update(delta, plan)
    # Decoding a delta against the WRONG baseline reconstructs a
    # different tree — the digest check refuses it.
    bad_base = [np.zeros_like(x) for x in leaves]
    with pytest.raises(ValueError, match="digest mismatch"):
        decode_update(delta, plan, bad_base)
    good, _ = decode_update(delta, plan, leaves)
    for a, b in zip(good, jax.tree.leaves(pub.reconstruction())):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_lower_outer_step_is_a_graph_audit_surface():
    lowered = lower_outer_step(_host_tree(), outer_lr=0.7,
                               outer_momentum=0.9)
    txt = lowered.as_text()
    assert "diloco_outer_apply" in txt


# ---------------------------------------------------------------------------
# Chaos grammar: group-loss@N[:group=G].
# ---------------------------------------------------------------------------


def test_group_loss_parse_and_validation():
    from tpu_ddp.resilience.chaos import parse_faults
    (spec,) = parse_faults("group-loss@3:group=2")
    assert spec.kind == "group-loss" and spec.step == 3
    assert spec.group == 2 and spec.key.endswith(".group2")
    (spec,) = parse_faults("group-loss@1")
    assert spec.group is None
    with pytest.raises(ValueError, match="group-loss"):
        parse_faults("preempt@2:group=1")     # group= is ours alone
    with pytest.raises(ValueError, match=">= 0"):
        parse_faults("group-loss@2:group=-1")
    with pytest.raises(ValueError, match="unknown option"):
        parse_faults("group-loss@2:gruop=1")


def test_group_loss_fires_once_via_sentinel(tmp_path):
    from tpu_ddp.resilience.chaos import FaultInjector, parse_faults
    inj = FaultInjector(parse_faults("group-loss@2:group=1"), seed=0,
                        sentinel_dir=str(tmp_path), rank=0)
    assert inj.group_loss_fires(1) is None
    assert inj.group_loss_fires(2) == 1
    # One-shot: the sentinel blocks a replay of the same ordinal.
    assert inj.group_loss_fires(2) is None
    assert inj.group_loss_fires(3) is None
    default = FaultInjector(parse_faults("group-loss@1"), seed=0,
                            sentinel_dir=None, rank=0)
    assert default.group_loss_fires(1) == 0   # default lost gid
