"""Async dispatch pipeline (tpu_ddp/train/pipeline.py, round 6).

Three layers:

- :class:`DispatchPipeline` unit semantics on fake handles — FIFO
  delivery, depth-0 synchronous degeneration, the ≤1-forced-sync-per-
  ``depth``-steps drain discipline;
- the engine's streaming loop under ``cfg.dispatch_depth > 0`` — log
  parity with the synchronous loop, step-ordered accounting, the
  delayed-divergence contract (TrainingDivergedError at most ``depth``
  steps late), and the sync-count regression (monkeypatched
  ``jax.block_until_ready``);
- composition knobs — TPU_DDP_DISPATCH_DEPTH env parsing, prefetch
  depth validation, and which fault kinds disable device prefetch
  (only host-side batch poisoning; docs/DESIGN.md §13).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.vgg import VGGModel
from tpu_ddp.train.engine import Trainer
from tpu_ddp.train.pipeline import DispatchPipeline
from tpu_ddp.utils.config import TrainConfig


class FakeHandle:
    """Stands in for a device array: pollable, blockable readiness."""

    def __init__(self, ready=False):
        self.ready = ready

    def is_ready(self):
        return self.ready

    def block_until_ready(self):
        self.ready = True
        return self


class TestDispatchPipelineUnit:
    def test_negative_depth_rejected(self):
        with pytest.raises(ValueError, match="depth must be >= 0"):
            DispatchPipeline(-1)

    def test_depth_zero_is_synchronous(self):
        """Every submit delivers before returning — even a handle that
        never polls ready (the forced drain blocks on it)."""
        pipe = DispatchPipeline(0)
        got = []
        for i in range(3):
            pipe.submit(FakeHandle(ready=False), lambda v, i=i: got.append(i))
            assert got == list(range(i + 1))
        assert len(pipe) == 0
        assert pipe.stats()["forced_syncs"] == 3
        assert pipe.stats()["max_in_flight"] == 1

    def test_fifo_head_blocks_delivery(self):
        """A ready handle behind an unready head must wait: delivery is
        strictly in submission order (the harvested-results consumers —
        loss window, guard, heartbeat — assume it)."""
        pipe = DispatchPipeline(3)
        h0, h1 = FakeHandle(ready=False), FakeHandle(ready=True)
        got = []
        pipe.submit(h0, lambda v: got.append(0))
        pipe.submit(h1, lambda v: got.append(1))
        assert got == []  # h1 ready, but h0 gates the queue
        h0.ready = True
        pipe.poll()
        assert got == [0, 1]
        assert pipe.stats()["forced_syncs"] == 0

    def test_one_forced_sync_per_window_overflow(self):
        """depth unready submits ride free; the (depth+1)-th triggers ONE
        blocking drain of the whole window."""
        pipe = DispatchPipeline(2)
        got = []
        for i in range(3):
            pipe.submit(FakeHandle(ready=False),
                        lambda v, i=i: got.append(i))
        assert got == [0, 1, 2]
        s = pipe.stats()
        assert s["forced_syncs"] == 1
        assert s["harvested"] == 3
        assert s["max_in_flight"] == 3
        assert s["host_gap_ms"] >= 0.0

    def test_sync_submit_flushes_backlog_and_itself(self):
        """sync=True delivers the backlog and the new handle, but is
        charged to sync_deliveries, NOT the async window's forced-sync
        or host-gap accounting: the caller only uses that path after
        blocking on the handle itself (the timing protocol), so the
        drain is free."""
        pipe = DispatchPipeline(4)
        got = []
        pipe.submit(FakeHandle(ready=False), lambda v: got.append(0))
        pipe.submit(FakeHandle(ready=False), lambda v: got.append(1),
                    sync=True)
        assert got == [0, 1]
        s = pipe.stats()
        assert s["sync_deliveries"] == 1
        assert s["forced_syncs"] == 0
        assert s["host_gap_ms"] == 0.0

    def test_sync_submit_at_depth_zero_counts_forced(self):
        """Depth 0 is the synchronous baseline: even sync=True submits
        (the engine's depth-0 path) keep the per-step forced-sync
        accounting the depth sweep measures against."""
        pipe = DispatchPipeline(0)
        got = []
        pipe.submit(FakeHandle(ready=False), lambda v: got.append(0),
                    sync=True)
        assert got == [0]
        assert pipe.stats()["forced_syncs"] == 1
        assert pipe.stats()["sync_deliveries"] == 0

    def test_drain_empties_and_is_noop_when_empty(self):
        pipe = DispatchPipeline(4)
        got = []
        pipe.submit(FakeHandle(ready=False), lambda v: got.append(0))
        pipe.drain()
        assert got == [0]
        pipe.drain()  # empty: must not count a forced sync
        assert pipe.stats()["forced_syncs"] == 1

    def test_raising_callback_propagates_keeps_rest_queued(self):
        """A diverging step's callback raises out of the drain; handles
        behind it stay queued (and die with the trainer — their steps
        never reached any harvested-results consumer)."""
        pipe = DispatchPipeline(4)

        def boom(v):
            raise RuntimeError("diverged")

        pipe.submit(FakeHandle(ready=False), boom)
        pipe.submit(FakeHandle(ready=False), lambda v: None)
        with pytest.raises(RuntimeError, match="diverged"):
            pipe.drain()
        assert len(pipe) == 1


class TestDispatchDepthConfig:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("TPU_DDP_DISPATCH_DEPTH", "5")
        assert TrainConfig().dispatch_depth == 5

    def test_negative_rejected(self):
        with pytest.raises(ValueError, match="dispatch_depth"):
            TrainConfig(dispatch_depth=-1)

    def test_env_negative_rejected(self, monkeypatch):
        monkeypatch.setenv("TPU_DDP_DISPATCH_DEPTH", "-2")
        with pytest.raises(ValueError, match="dispatch_depth"):
            TrainConfig()


class TestPrefetchComposition:
    def test_negative_prefetch_depth_rejected(self):
        from tpu_ddp.data.prefetch import prefetch_to_device
        with pytest.raises(ValueError, match="prefetch depth"):
            list(prefetch_to_device([], lambda b: b, depth=-1))

    def test_poisons_batches_only_for_nan_grad(self):
        from tpu_ddp.resilience.chaos import FaultInjector, parse_faults
        assert FaultInjector(parse_faults("nan-grad@3")).poisons_batches
        for passive in ("slow-rank@3", "hard-exit@3", "corrupt-ckpt@3",
                        "stalled-step@3"):
            inj = FaultInjector(parse_faults(passive))
            assert inj.active and not inj.poisons_batches, passive

    @pytest.mark.parametrize("spec,expect_prefetch", [
        ("slow-rank@1", True),   # passive: composes with prefetch
        ("nan-grad@1", False),   # poisons a batch host-side: disables it
    ])
    def test_engine_disables_prefetch_only_for_poisoning(
            self, monkeypatch, spec, expect_prefetch):
        import tpu_ddp.train.engine as engine_mod
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS", spec)
        monkeypatch.setenv("TPU_DDP_CHAOS_SLOW_S", "0.001")
        called = []
        real = engine_mod.prefetch_to_device

        def spy(batches, put_fn, depth):
            called.append(depth)
            return real(batches, put_fn, depth)

        monkeypatch.setattr(engine_mod, "prefetch_to_device", spy)
        trainer = tiny_trainer(device_prefetch=2, guard_max_bad_steps=5)
        state = trainer.init_state()
        trainer.train_epoch(state, nan_after(3, bad_from=99)[0](),
                            log=lambda s: None)
        assert bool(called) is expect_prefetch


def tiny_trainer(**kw):
    model = VGGModel(name="tiny", cfg=(8, "M", 16, "M"),
                     compute_dtype=jnp.float32)
    return Trainer(model, TrainConfig(**kw), strategy="none")


def small_batches(n, bs=16, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.normal(0, 0.1, size=(bs, 4, 4, 3)).astype(np.float32),
             rng.integers(0, 10, size=bs).astype(np.int32))
            for _ in range(n)]


def nan_after(n, bs=16, bad_from=1):
    """A counting generator factory: batches ``bad_from`` onward are all
    NaN. Returns (make_gen, consumed) — ``consumed[0]`` counts how many
    batches the epoch loop actually pulled, which bounds how far the
    loop ran past the diverging step."""
    consumed = [0]

    def gen():
        for i, (x, y) in enumerate(small_batches(n, bs=bs)):
            consumed[0] += 1
            if i >= bad_from:
                x = np.full_like(x, np.nan)
            yield x, y

    return gen, consumed


class TestAsyncEpoch:
    def _filtered(self, lines):
        # The timing report embeds measured wall-clock ns — the one
        # line that legitimately differs between runs.
        return [l for l in lines if "timing over iterations" not in l]

    def test_log_and_loss_parity_across_depths(self):
        """The async loop must print the same lines and account the
        same losses as the synchronous one — just later."""
        runs = {}
        for depth in (0, 3):
            trainer = tiny_trainer(log_every=2, timing_first_iter=1,
                                   timing_last_iter=2,
                                   dispatch_depth=depth)
            lines = []
            _, stats = trainer.train_epoch(trainer.init_state(),
                                           small_batches(8),
                                           log=lines.append)
            runs[depth] = (self._filtered(lines), stats)
        lines0, stats0 = runs[0]
        lines3, stats3 = runs[3]
        assert lines0 == lines3
        assert stats0["last_loss"] == pytest.approx(
            stats3["last_loss"], abs=1e-6)
        assert stats0["iters"] == stats3["iters"] == 8
        assert stats3["forced_syncs"] < stats0["forced_syncs"]

    def test_guard_records_in_step_order(self):
        """Harvest order == step order (FIFO pipeline): the guard sees
        steps 1..N exactly, each once, even at depth > 0."""
        trainer = tiny_trainer(dispatch_depth=2, timing_first_iter=1,
                               timing_last_iter=0)
        seen = []

        class Recorder:
            def record(self, step, skipped, loss):
                seen.append((step, skipped))

        trainer.guard = Recorder()
        trainer.train_epoch(trainer.init_state(), small_batches(7),
                            log=lambda s: None)
        assert [s for s, _ in seen] == list(range(1, 8))
        assert not any(sk for _, sk in seen)

    def test_divergence_raises_at_most_depth_late(self):
        """The delayed-divergence contract (docs/DESIGN.md §13): K
        consecutive NaN steps raise at HARVEST, at most dispatch_depth
        steps after the K-th bad step was dispatched — bounded here by
        counting how many batches the loop consumed."""
        from tpu_ddp.resilience.guard import TrainingDivergedError
        depth, max_bad = 2, 2
        trainer = tiny_trainer(dispatch_depth=depth,
                               guard_max_bad_steps=max_bad,
                               timing_first_iter=1, timing_last_iter=0)
        make_gen, consumed = nan_after(12, bad_from=1)
        with pytest.raises(TrainingDivergedError):
            trainer.train_epoch(trainer.init_state(), make_gen(),
                                log=lambda s: None)
        # 1 clean + max_bad to trip the guard + at most `depth` extra
        # dispatches before the tripping step is harvested (+1 for the
        # batch pulled in the same iteration the raise surfaces).
        assert consumed[0] <= 1 + max_bad + depth + 1, consumed[0]

    def test_at_most_one_forced_sync_per_depth_steps(self, monkeypatch):
        """Regression for the whole point of the pipeline: the streaming
        loop may force at most one device sync per ``depth`` steps
        (plus the timing-window iteration and the end-of-epoch drain).
        The synchronous loop pays one PER STEP."""
        depth, iters = 2, 9
        trainer = tiny_trainer(dispatch_depth=depth, timing_first_iter=1,
                               timing_last_iter=0)
        state = trainer.init_state()
        calls = {0: 0}
        real = jax.block_until_ready

        def counting(x):
            calls[0] += 1
            return real(x)

        monkeypatch.setattr(jax, "block_until_ready", counting)
        trainer.train_epoch(state, small_batches(iters),
                            log=lambda s: None)
        # 1 sync timing iteration (iter 0) + <= (iters-1)/depth forced
        # drains + 1 final drain; opportunistic polling only reduces it.
        assert calls[0] <= 1 + (iters - 1) // depth + 1, calls[0]
        assert calls[0] < iters

    def test_pipeline_stats_and_host_gap_gauge(self):
        trainer = tiny_trainer(dispatch_depth=2, timing_first_iter=1,
                               timing_last_iter=0)
        _, stats = trainer.train_epoch(trainer.init_state(),
                                       small_batches(6),
                                       log=lambda s: None)
        assert stats["dispatch_depth"] == 2
        assert stats["harvested"] == 6
        # Timing iter 0 pre-blocks and lands in sync_deliveries;
        # forced_syncs counts only window-caused drains (may be 0 when
        # every handle polls ready before the window fills).
        assert stats["sync_deliveries"] == 1
        assert stats["forced_syncs"] >= 0
        assert stats["host_gap_ms"] >= 0.0
        g = trainer.metrics.gauge_summary("host_gap_ms")
        assert g is not None and g["count"] == 1
        assert g["last"] == stats["host_gap_ms"]

    def test_multiprocess_cadence_forces_sync_window(self, monkeypatch,
                                                     tmp_path):
        """The in-loop checkpoint/replica cadences enqueue CROSS-HOST
        collectives (state gather / digest allgather) from on_harvest,
        and harvest timing is per-process — so a multi-process run with
        such a cadence configured must fall back to the synchronous
        window (depth 0) to keep collective order and the snapshotted
        state step identical on every process (docs/DESIGN.md §13)."""
        trainer = tiny_trainer(dispatch_depth=4, ckpt_every_iters=100,
                               timing_first_iter=1, timing_last_iter=0)
        state = trainer.init_state()
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        _, stats = trainer.train_epoch(state, small_batches(4),
                                       ckpt_dir=str(tmp_path),
                                       log=lambda s: None)
        assert stats["dispatch_depth"] == 0
        # Single process the same cadence keeps the async window — the
        # ahead-of-harvest state is safe there (skipped steps are
        # no-ops; checkpoints are stamped with their own step).
        monkeypatch.setattr(jax, "process_count", lambda: 1)
        trainer2 = tiny_trainer(dispatch_depth=4, ckpt_every_iters=100,
                                timing_first_iter=1, timing_last_iter=0)
        _, stats2 = trainer2.train_epoch(trainer2.init_state(),
                                         small_batches(4),
                                         ckpt_dir=str(tmp_path / "sp"),
                                         log=lambda s: None)
        assert stats2["dispatch_depth"] == 4

    def test_chaos_env_forces_synchronous_window(self, monkeypatch):
        """Active chaos must run depth 0 regardless of config: faults
        land on exact steps and divergence surfaces immediately."""
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS", "slow-rank@2")
        monkeypatch.setenv("TPU_DDP_CHAOS_SLOW_S", "0.001")
        trainer = tiny_trainer(dispatch_depth=4, timing_first_iter=1,
                               timing_last_iter=0)
        _, stats = trainer.train_epoch(trainer.init_state(),
                                       small_batches(4),
                                       log=lambda s: None)
        assert stats["dispatch_depth"] == 0
        # Depth 0 keeps the synchronous baseline's accounting: every
        # delivery is a forced sync, none are booked as sync_deliveries.
        assert stats["forced_syncs"] == stats["harvested"] == 4
        assert stats["sync_deliveries"] == 0
