"""Strategy-equivalence tests — the core correctness property of the ladder.

The reference's invariants (report §2.2, SURVEY.md §1 L1): identical init on
all replicas + synchronized gradients before each step => all four
strategies yield identical parameter trajectories, and (with equal shards)
identical to single-device training on the full batch. The reference never
tested this; we do, on a 4-device virtual mesh (SURVEY.md §4).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from tpu_ddp.models.vgg import VGGModel
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig

DISTRIBUTED = ["gather_scatter", "all_reduce", "fused"]


def tiny_model():
    # 4x4 inputs, two conv blocks + two pools -> 1x1x16 -> head. Same
    # builder as VGG11, small enough for fast CPU tests.
    return VGGModel(name="tiny", cfg=(8, "M", 16, "M"),
                    compute_dtype=jnp.float32)


@dataclasses.dataclass(frozen=True)
class TinyNoBN:
    """Conv+pool+dense model with NO BatchNorm.

    BN couples examples through batch statistics, so per-replica BN stats
    (the reference's deliberate semantic, report §3.2) make distributed
    forward passes differ from a single-device full-batch pass. To verify
    the *gradient-sync math* in isolation we need a per-example-decoupled
    model; BN-specific divergence is covered separately below.
    """

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {
            "conv": 0.3 * jax.random.normal(k1, (3, 3, 3, 8)),
            "bias": jnp.zeros((8,)),
            "head": 0.3 * jax.random.normal(k2, (2 * 2 * 8, 10)),
            "head_b": 0.01 * jax.random.normal(k3, (10,)),
        }

    def apply(self, params, x):
        y = lax.conv_general_dilated(
            x, params["conv"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = jnp.maximum(y + params["bias"], 0)
        y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 2, 2, 1),
                              (1, 2, 2, 1), "VALID")
        return y.reshape(y.shape[0], -1) @ params["head"] + params["head_b"]


def batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4, 4, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


def run_steps(trainer, n_steps=3):
    state = trainer.init_state()
    losses = []
    for i in range(n_steps):
        x, y = batch(seed=i)
        xb, yb, wb = trainer.put_batch(x, y)
        state, loss = trainer.train_step(state, xb, yb, wb)
        losses.append(np.ravel(np.asarray(loss)))
    return state, losses


def params_allclose(a, b, **kw):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb), **kw)


@pytest.mark.parametrize("strategy", DISTRIBUTED)
def test_distributed_matches_single_device(strategy, devices):
    """Each distributed rung == part1 on the full batch (equal shards).

    Holds exactly for a per-example-decoupled model: mean of shard-mean
    gradients over equal shards == full-batch mean gradient.
    """
    model = TinyNoBN()
    single = Trainer(model, TrainConfig(), strategy="none", mesh=None)
    state_s, _ = run_steps(single)

    mesh = make_mesh(devices[:4])
    dist = Trainer(model, TrainConfig(), strategy=strategy, mesh=mesh)
    state_d, _ = run_steps(dist)

    params_allclose(state_s.params, state_d.params, rtol=1e-5, atol=1e-6)


def test_bn_models_diverge_from_single_device_by_design(devices):
    """Documents the reference's BN semantic (report §3.2): per-replica
    batch statistics make the distributed forward differ from the
    single-device full-batch forward — divergence is EXPECTED with BN
    (``track_running_stats=False``), while replicas still agree with each
    other (test_all_strategies_agree_pairwise)."""
    model = tiny_model()  # has BN
    single = Trainer(model, TrainConfig(), strategy="none", mesh=None)
    state_s, _ = run_steps(single, n_steps=1)
    mesh = make_mesh(devices[:4])
    dist = Trainer(model, TrainConfig(), strategy="fused", mesh=mesh)
    state_d, _ = run_steps(dist, n_steps=1)
    leaves_s = jax.tree.leaves(state_s.params)
    leaves_d = jax.tree.leaves(state_d.params)
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7)
        for a, b in zip(leaves_s, leaves_d))


def test_all_strategies_agree_pairwise(devices):
    mesh = make_mesh(devices[:4])
    model = tiny_model()
    results = {}
    for s in DISTRIBUTED:
        results[s] = run_steps(Trainer(model, TrainConfig(), strategy=s,
                                       mesh=mesh))[0]
    for s in DISTRIBUTED[1:]:
        params_allclose(results[DISTRIBUTED[0]].params, results[s].params,
                        rtol=1e-5, atol=1e-6)


def test_replicas_stay_in_sync(devices):
    """Invariant (ii): after sync'd steps, params are identical across
    replicas — i.e. the replicated output sharding is truthful."""
    mesh = make_mesh(devices[:4])
    trainer = Trainer(tiny_model(), TrainConfig(), strategy="fused",
                      mesh=mesh)
    state, _ = run_steps(trainer, n_steps=2)
    leaf = jax.tree.leaves(state.params)[0]
    shards = [np.asarray(s.data) for s in leaf.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_ragged_batch_matches_single_device(devices):
    """A final batch not divisible by dp slots (drop_last=False semantics,
    reference part1/main.py:36-41) is wrap-padded with zero weights —
    updates must equal the single-device run on the unpadded batch."""
    model = TinyNoBN()
    rng = np.random.default_rng(42)
    x = rng.normal(size=(18, 4, 4, 3)).astype(np.float32)  # 18 % 4 != 0
    y = rng.integers(0, 10, size=18).astype(np.int32)

    single = Trainer(model, TrainConfig(), strategy="none", mesh=None)
    s_state = single.init_state()
    s_state, _ = single.train_step(s_state, *single.put_batch(x, y))

    mesh = make_mesh(devices[:4])
    dist = Trainer(model, TrainConfig(), strategy="fused", mesh=mesh)
    d_state = dist.init_state()
    xb, yb, wb = dist.put_batch(x, y)
    assert xb.shape[0] == 20  # padded to the next multiple of 4
    d_state, _ = dist.train_step(d_state, xb, yb, wb)

    params_allclose(s_state.params, d_state.params, rtol=1e-5, atol=1e-6)


def test_per_replica_losses_reported(devices):
    mesh = make_mesh(devices[:4])
    trainer = Trainer(tiny_model(), TrainConfig(), strategy="all_reduce",
                      mesh=mesh)
    _, losses = run_steps(trainer, n_steps=1)
    assert losses[0].shape == (4,)  # one loss per dp slot


class TestStrategyLookup:
    """Name-resolution error contract (sync.py). ``canonical_strategy``
    must reject unknown ``part*`` aliases itself — the old pass-through
    deferred the failure to ``get_sync_strategy``'s dict lookup, and a
    caller comparing only the canonical name would silently treat
    'part9' as the no-sync strategy."""

    def test_unknown_part_alias_rejected(self):
        from tpu_ddp.parallel.sync import canonical_strategy
        with pytest.raises(ValueError, match=r"unknown part alias 'part9'"):
            canonical_strategy("part9")
        with pytest.raises(ValueError, match=r"available parts"):
            canonical_strategy("part0")

    def test_known_names_resolve(self):
        from tpu_ddp.parallel.sync import canonical_strategy
        assert canonical_strategy("part4") == "zero"
        assert canonical_strategy("fused") == "fused"
        # Non-part junk passes through: get_sync_strategy owns that error.
        assert canonical_strategy("bogus") == "bogus"

    def test_get_sync_strategy_error_lists_options(self):
        from tpu_ddp.parallel.sync import (PART_TO_STRATEGY,
                                           SYNC_STRATEGIES,
                                           get_sync_strategy)
        with pytest.raises(ValueError) as ei:
            get_sync_strategy("bogus")
        msg = str(ei.value)
        assert msg.startswith("unknown sync strategy 'bogus'")
        assert str(sorted(SYNC_STRATEGIES)) in msg
        assert str(sorted(PART_TO_STRATEGY)) in msg

    def test_get_sync_strategy_part_alias_error(self):
        from tpu_ddp.parallel.sync import get_sync_strategy
        with pytest.raises(ValueError, match=r"unknown part alias"):
            get_sync_strategy("part7")
