"""Memory-policy subsystem (tpu_ddp/memory/): remat + act_dtype.

What the policy layer must guarantee, each pinned here:

- the policy vocabulary validates at every surface (helpers, model
  construction, TrainConfig env parse) — a typo'd policy raises, never
  silently trains the default;
- gradients under every remat policy match the remat=none program
  (recompute re-executes the SAME ops; only what autodiff saves
  changes) — per family, tiny f32 models;
- ``act_dtype`` changes numerics only through the saved boundary
  round-trip (bf16 boundaries under f32 compute: small, bounded drift);
- the deprecated ``remat_blocks`` alias resolves through
  ``remat_policy`` and the LM-large preset still gets block remat;
- the config-level knobs imprint onto models at Trainer construction
  (env -> TrainConfig -> apply_policy) without downgrading explicit
  model policies;
- the policied program composes with the engine surfaces: StepGuard
  skip-rollback, the K-step scan, the streaming loop at
  dispatch_depth>0, and the grad_compress EF carry (slow tier);
- the motivating LM claim: plain (non-grad-accum) batch-256 LM-small
  compiles under remat=blocks with a strictly smaller XLA temp-buffer
  peak than remat=none (slow tier; abstract AOT compile, no buffers
  materialize).
"""

import dataclasses
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.memory import (ACT_DTYPES, REMAT_POLICIES, apply_policy,
                            cast_saved, checkpoint_policy,
                            effective_remat, family_for_model,
                            resolve_act_dtype, validate_act_dtype,
                            validate_remat, wrap_stage)
from tpu_ddp.models import make_transformer, make_vit
from tpu_ddp.models.resnet import ResNetModel
from tpu_ddp.models.vgg import VGGModel


# ---------------------------------------------------------------------
# tiny per-family models (f32: equivalence must not hide in bf16 noise)
# ---------------------------------------------------------------------

def _tiny_vgg(**kw):
    return VGGModel(name="VGG-test", cfg=(8, "M", 16, "M"),
                    num_classes=4, compute_dtype=jnp.float32, **kw)


def _tiny_resnet(**kw):
    return ResNetModel(name="ResNet-test", stage_blocks=(1, 1),
                       num_classes=4, small_inputs=True,
                       compute_dtype=jnp.float32, **kw)


def _tiny_vit(**kw):
    return make_vit("ViT-tiny", image_size=8, patch_size=4,
                    num_layers=2, num_heads=2, d_model=16, d_ff=32,
                    num_classes=4, compute_dtype=jnp.float32, **kw)


def _tiny_lm(**kw):
    return make_transformer("TransformerLM-tiny", max_seq_len=16,
                            compute_dtype=jnp.float32, **kw)


_FAMILIES = {
    "vgg": (_tiny_vgg, lambda: np.random.default_rng(0).normal(
        size=(2, 4, 4, 3)).astype(np.float32)),
    "resnet": (_tiny_resnet, lambda: np.random.default_rng(0).normal(
        size=(2, 8, 8, 3)).astype(np.float32)),
    "vit": (_tiny_vit, lambda: np.random.default_rng(0).normal(
        size=(2, 8, 8, 3)).astype(np.float32)),
    "lm": (_tiny_lm, lambda: np.random.default_rng(0).integers(
        0, 1024, size=(2, 16)).astype(np.int32)),
}


def _loss_and_grads(model, x):
    params = model.init(jax.random.key(0))

    def loss(p):
        out = model.apply(p, jnp.asarray(x))
        return jnp.mean(jnp.square(out.astype(jnp.float32)))

    value, grads = jax.jit(jax.value_and_grad(loss))(params)
    return float(value), grads


def _assert_grads_close(ga, gb, rtol=1e-4, atol=1e-6):
    la, lb = jax.tree.leaves(ga), jax.tree.leaves(gb)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol)


# ---------------------------------------------------------------------
# policy helpers
# ---------------------------------------------------------------------

class TestHelpers:
    def test_vocabulary(self):
        assert set(REMAT_POLICIES) == {"none", "blocks", "conv_stages",
                                       "dots"}
        assert set(ACT_DTYPES) == {"compute", "bf16", "f32"}
        for v in REMAT_POLICIES:
            assert validate_remat(v) == v
        for v in ACT_DTYPES:
            assert validate_act_dtype(v) == v

    def test_junk_rejected_naming_the_env_surface(self):
        with pytest.raises(ValueError, match="TPU_DDP_REMAT"):
            validate_remat("junk")
        with pytest.raises(ValueError, match="TPU_DDP_ACT_DTYPE"):
            validate_act_dtype("fp8")

    def test_resolve_act_dtype(self):
        assert resolve_act_dtype("compute", jnp.bfloat16) == jnp.bfloat16
        assert resolve_act_dtype("compute", jnp.float32) == jnp.float32
        assert resolve_act_dtype("bf16", jnp.float32) == jnp.bfloat16
        assert resolve_act_dtype("f32", jnp.bfloat16) == jnp.float32

    def test_cast_saved_matching_dtype_is_identity(self):
        # The default policy must trace the EXACT pre-policy program:
        # astype to the same dtype returns the operand, no convert op.
        x = jnp.ones((3,), jnp.float32)
        assert cast_saved(x, "compute", jnp.float32) is x
        assert cast_saved(x, "f32", jnp.float32) is x
        assert cast_saved(x, "bf16", jnp.float32).dtype == jnp.bfloat16

    def test_checkpoint_policy(self):
        assert checkpoint_policy("dots") is \
            jax.checkpoint_policies.dots_saveable
        assert checkpoint_policy("blocks") is None
        assert checkpoint_policy("conv_stages") is None

    def test_wrap_stage_none_is_identity(self):
        def f(x):
            return x * 2
        assert wrap_stage(f, "none") is f

    def test_wrap_stage_blocks_is_checkpoint(self):
        f = wrap_stage(lambda x: jnp.sin(x) * 2, "blocks")
        jaxpr = str(jax.make_jaxpr(jax.grad(f))(1.0))
        assert "remat" in jaxpr or "checkpoint" in jaxpr

    def test_effective_remat_degrades_conv_stages_on_attn(self):
        with pytest.warns(UserWarning, match="conv_stages"):
            assert effective_remat("conv_stages", "attn") == "blocks"
        assert effective_remat("conv_stages", "conv") == "conv_stages"
        assert effective_remat("dots", "conv") == "dots"
        assert effective_remat("none", "attn") == "none"

    def test_family_for_model(self):
        assert family_for_model("VGG11") == "conv"
        assert family_for_model("ResNet50") == "conv"
        assert family_for_model("ViT-tiny") == "attn"
        assert family_for_model("TransformerLM-small") == "attn"
        assert family_for_model("SomethingElse") == ""


class TestApplyPolicy:
    def test_defaults_are_identity(self):
        m = _tiny_vgg()
        assert apply_policy(m) is m

    def test_imprints_non_default(self):
        m = apply_policy(_tiny_vgg(), remat="blocks", act_dtype="bf16")
        assert m.remat == "blocks" and m.act_dtype == "bf16"

    def test_never_downgrades_explicit_model_policy(self):
        # Config defaults (remat="none") must not strip the LM-large
        # preset's built-in block remat.
        m = _tiny_lm(remat="blocks")
        assert apply_policy(m, remat="none").remat == "blocks"

    def test_non_default_config_wins(self):
        m = _tiny_lm(remat="blocks")
        assert apply_policy(m, remat="dots").remat == "dots"

    def test_warns_and_ignores_model_without_fields(self):
        @dataclasses.dataclass(frozen=True)
        class NoPolicy:
            pass
        m = NoPolicy()
        with pytest.warns(UserWarning, match="NoPolicy"):
            assert apply_policy(m, remat="blocks") is m

    def test_model_constructor_validates(self):
        with pytest.raises(ValueError, match="remat"):
            _tiny_vgg(remat="junk")
        with pytest.raises(ValueError, match="act_dtype"):
            _tiny_resnet(act_dtype="fp8")


class TestAlias:
    def test_remat_blocks_alias_resolves(self):
        assert _tiny_lm(remat_blocks=True).remat_policy == "blocks"
        assert _tiny_lm().remat_policy == "none"
        assert _tiny_lm(remat="dots").remat_policy == "dots"

    def test_lm_large_preset_keeps_block_remat(self):
        # Construction only (the ~740M-param init never runs).
        assert make_transformer("TransformerLM-large").remat_policy \
            == "blocks"


# ---------------------------------------------------------------------
# gradient equivalence: remat re-executes, never changes, the math
# ---------------------------------------------------------------------

class TestGradientEquivalence:
    _cache = {}

    def _baseline(self, family):
        if family not in self._cache:
            build, data = _FAMILIES[family]
            self._cache[family] = _loss_and_grads(build(), data())
        return self._cache[family]

    # Tier-1 keeps exactly ONE equivalence cell — the vgg baseline is
    # the cheapest compile and conv_stages exercises the real
    # jax.checkpoint wrapping path; every other (family, policy) cell
    # runs in the slow tier (the 870 s tier-1 wall-clock budget has
    # ~20 s of headroom over the seed suite on a single-core host).
    @pytest.mark.parametrize("family,remat", [
        ("vgg", "conv_stages"),
    ])
    def test_core_cells(self, family, remat):
        l0, g0 = self._baseline(family)
        build, data = _FAMILIES[family]
        l1, g1 = _loss_and_grads(build(remat=remat), data())
        assert np.isclose(l0, l1, rtol=1e-5)
        _assert_grads_close(g0, g1)

    @pytest.mark.slow  # 8 more tiny-model grad compiles
    @pytest.mark.parametrize("family,remat", [
        ("vgg", "blocks"), ("lm", "blocks"), ("lm", "dots"),
        ("resnet", "blocks"), ("resnet", "conv_stages"),
        ("resnet", "dots"), ("vit", "blocks"), ("vit", "dots"),
        ("vgg", "dots"),
    ])
    def test_remaining_cells(self, family, remat):
        l0, g0 = self._baseline(family)
        build, data = _FAMILIES[family]
        l1, g1 = _loss_and_grads(build(remat=remat), data())
        assert np.isclose(l0, l1, rtol=1e-5)
        _assert_grads_close(g0, g1)

    @pytest.mark.slow  # one more tiny-vgg grad compile
    def test_act_dtype_bf16_bounded_drift(self):
        # bf16 boundaries under f32 compute: the ONLY numeric change is
        # the saved-boundary round-trip, so gradients sit within bf16's
        # ~3 decimal digits of the f32 program — close but NOT equal
        # (equality would mean the cast never happened).
        l0, g0 = self._baseline("vgg")
        l1, g1 = _loss_and_grads(_tiny_vgg(remat="blocks",
                                           act_dtype="bf16"),
                                 _FAMILIES["vgg"][1]())
        assert np.isclose(l0, l1, rtol=2e-2)
        _assert_grads_close(g0, g1, rtol=5e-2, atol=5e-3)

    @pytest.mark.slow  # one more tiny-LM grad compile
    def test_conv_stages_on_attn_degrades_equivalently(self):
        with pytest.warns(UserWarning, match="conv_stages"):
            l1, g1 = _loss_and_grads(_tiny_lm(remat="conv_stages"),
                                     _FAMILIES["lm"][1]())
        l0, g0 = self._baseline("lm")
        assert np.isclose(l0, l1, rtol=1e-5)
        _assert_grads_close(g0, g1)


# ---------------------------------------------------------------------
# engine composition
# ---------------------------------------------------------------------

def _trainer(devices, dp=1, model=None, **cfg_kw):
    from tpu_ddp.parallel.mesh import make_mesh
    from tpu_ddp.train.engine import Trainer
    from tpu_ddp.utils.config import TrainConfig
    return Trainer(model if model is not None else _tiny_vgg(),
                   TrainConfig(**cfg_kw), strategy="fused",
                   mesh=make_mesh(devices[:dp]))


def _vgg_batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.normal(size=(n, 4, 4, 3)).astype(np.float32),
            rng.integers(0, 4, size=n).astype(np.int32))


def _flat(tree):
    return np.concatenate([np.ravel(np.asarray(jax.device_get(l)))
                           for l in jax.tree.leaves(tree)])


class TestEngineComposition:
    def test_env_knobs_imprint_through_trainer(self, devices,
                                               monkeypatch):
        monkeypatch.setenv("TPU_DDP_REMAT", "conv_stages")
        monkeypatch.setenv("TPU_DDP_ACT_DTYPE", "f32")
        tr = _trainer(devices)
        assert tr.model.remat == "conv_stages"
        assert tr.model.act_dtype == "f32"

    def test_config_junk_remat_rejected(self):
        from tpu_ddp.utils.config import TrainConfig
        with pytest.raises(ValueError, match="remat"):
            TrainConfig(remat="junk")
        with pytest.raises(ValueError, match="act_dtype"):
            TrainConfig(act_dtype="junk")

    @pytest.mark.slow  # two trainer compiles
    def test_trajectory_matches_none(self, devices):
        def run(remat):
            tr = _trainer(devices, remat=remat)
            state = tr.init_state()
            for i in range(2):
                state, loss = tr.train_step(
                    state, *tr.put_batch(*_vgg_batch(seed=i)))
            return _flat(state.params), float(
                np.ravel(np.asarray(loss))[0])
        p0, l0 = run("none")
        p1, l1 = run("conv_stages")
        assert np.isclose(l0, l1, rtol=1e-5)
        np.testing.assert_allclose(p0, p1, rtol=1e-4, atol=1e-6)

    @pytest.mark.slow  # one trainer compile
    def test_step_guard_skip_rolls_back_under_remat(self, devices):
        tr = _trainer(devices, remat="blocks")
        state = tr.init_state()
        state, _ = tr.train_step(state, *tr.put_batch(*_vgg_batch()))
        p0 = _flat(state.params)
        x, y = _vgg_batch(seed=5)
        x[0, 0, 0, 0] = np.nan
        state, _ = tr.train_step(state, *tr.put_batch(x, y))
        assert tr.last_step_skipped()
        np.testing.assert_array_equal(p0, _flat(state.params))

    @pytest.mark.slow  # two trainer compiles (scan + single)
    def test_multi_step_scan_matches_single_steps(self, devices):
        tr = _trainer(devices, remat="blocks")
        state = tr.init_state()
        for i in range(2):
            state, _ = tr.train_step(state,
                                     *tr.put_batch(*_vgg_batch(seed=i)))
        tr2 = _trainer(devices, remat="blocks")
        s2 = tr2.init_state()
        xs, ys = zip(*[_vgg_batch(seed=i) for i in range(2)])
        s2, _ = tr2.build_multi_step(2)(
            s2, *tr2.put_batches(np.stack(xs), np.stack(ys)))
        np.testing.assert_allclose(_flat(state.params),
                                   _flat(s2.params),
                                   rtol=1e-6, atol=1e-7)

    @pytest.mark.slow  # streaming epoch at depth 2
    def test_dispatch_depth_streams_under_remat(self, devices):
        tr = _trainer(devices, remat="blocks", dispatch_depth=2)
        state = tr.init_state()

        def gen():
            for i in range(5):
                yield _vgg_batch(seed=i)
        state, stats = tr.train_epoch(state, gen(), epoch=0)
        assert np.all(np.isfinite(_flat(state.params)))

    @pytest.mark.slow  # dp=4 compile with int8 wire + remat
    def test_grad_compress_ef_carry_composes(self, devices):
        """int8 wire + error-feedback carry + block remat in ONE step:
        the policied grads are what the compressor sees, and the
        recompute must not perturb the deterministic EF trajectory
        (recompute re-executes identical ops -> same grads -> same
        quantization decisions)."""
        def run(remat):
            tr = _trainer(devices, dp=4, remat=remat,
                          grad_compress="int8")
            state = tr.init_state()
            for i in range(2):
                state, loss = tr.train_step(
                    state, *tr.put_batch(*_vgg_batch(seed=i)))
                jax.block_until_ready(state.params)
            return state
        s_remat = run("blocks")
        s_none = run("none")
        assert np.any(_flat(s_remat.comp_state["residual"]))
        np.testing.assert_allclose(
            _flat(s_remat.params), _flat(s_none.params),
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            _flat(s_remat.comp_state["residual"]),
            _flat(s_none.comp_state["residual"]),
            rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------
# the motivating LM claim: plain batch 256 compiles under block remat
# ---------------------------------------------------------------------

class TestLMPlainBatchCompile:
    @pytest.mark.slow  # two LM-small b=256 AOT compiles (~1-2 min)
    def test_batch_256_compiles_with_smaller_temp_peak(self):
        """EXPERIMENTS §8/§10: plain (non-grad-accum) LM-small batches
        > 32 failed to compile on the v5e — the saved-activation
        working set outgrows HBM. Block remat is the fix. The compile
        itself is abstract (jax.eval_shape params -> AOT lower), so
        this regression runs on hosts that could never hold the
        no-remat buffers; the temp-peak comparison is XLA's own buffer
        assignment, a platform-independent claim."""
        from scripts.remat_sweep import measure_lm_cell
        cells = {r: measure_lm_cell(batch=256, remat=r,
                                    with_time=False)
                 for r in ("none", "blocks")}
        for cell in cells.values():
            assert "error" not in cell
            assert cell.get("temp_bytes", 0) > 0
        # Whether the blocks program FITS a given HBM is a TPU-run
        # claim; the platform-independent regression is the ordering —
        # block remat must cut the temp peak decisively (measured ~2x
        # on this jaxlib; 0.75 leaves headroom for compiler drift).
        assert cells["blocks"]["temp_bytes"] \
            < 0.75 * cells["none"]["temp_bytes"]
