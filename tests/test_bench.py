"""bench.py — the driver's benchmark entry point.

Guards the contract the driver depends on: every config produces one
dict with metric/value/unit/vs_baseline, shrunk to smoke size here
(real numbers come from the TPU run).
"""

import numpy as np
import pytest

import bench


class TestBenchEntry:
    def test_headline_vgg_contract(self):
        out = bench.run_bench(batch_size=8, timed_iters=2,
                              config="vgg11_cifar10")
        assert out["metric"] == "cifar10_vgg11_images_per_sec_per_chip"
        assert out["unit"] == "images/sec"
        assert out["value"] > 0 and np.isfinite(out["value"])
        # Tolerance, not equality: value is rounded to 0.1 before this
        # check while vs_baseline was rounded from the unrounded rate.
        assert abs(out["vs_baseline"] - out["value"] / 386.0) < 0.01
        assert out["extra"]["timed_iters"] == 2

    def test_vit_config(self):
        out = bench.run_bench(batch_size=8, timed_iters=2,
                              config="vit_cifar10")
        assert out["metric"] == "cifar10_vit-tiny_images_per_sec_per_chip"
        assert out["vs_baseline"] is None  # no reference number exists
        assert out["value"] > 0

    def test_lm_config(self):
        out = bench.run_lm_bench(batch_size=2, seq_len=64, timed_iters=2)
        assert out["metric"] == "transformer_lm_tokens_per_sec_per_chip"
        assert out["unit"] == "tokens/sec"
        assert out["value"] > 0 and np.isfinite(out["value"])

    def test_unknown_config_rejected(self):
        with pytest.raises(ValueError, match="unknown preset"):
            bench.run_bench(config="resnet9000")
