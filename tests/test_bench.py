"""bench.py — the driver's benchmark entry point.

Guards the contract the driver depends on: every config produces one
dict with metric/value/unit/vs_baseline, shrunk to smoke size here
(real numbers come from the TPU run).
"""

import numpy as np
import pytest

import bench


class TestBenchEntry:
    @pytest.mark.slow  # full bench entrypoint run; the config plumbing is
    # covered fast by test_lm_config
    def test_headline_vgg_contract(self):
        # with_xla_flops=False skips the AOT cost-analysis recompile
        # (seconds on this host); the xla-flops path has its own test
        # below on the tiniest config.
        out = bench.run_bench(batch_size=8, timed_iters=2,
                              config="vgg11_cifar10",
                              with_xla_flops=False, end_to_end_iters=1)
        assert out["metric"] == "cifar10_vgg11_images_per_sec_per_chip"
        assert out["unit"] == "images/sec"
        assert out["value"] > 0 and np.isfinite(out["value"])
        # Tolerance, not equality: value is rounded to 0.1 before this
        # check while vs_baseline was rounded from the unrounded rate.
        assert abs(out["vs_baseline"] - out["value"] / 386.0) < 0.01
        assert out["extra"]["timed_iters"] == 2

    @pytest.mark.slow  # ViT compile: model correctness lives in test_vit
    def test_vit_config(self):
        out = bench.run_bench(batch_size=8, timed_iters=2,
                              config="vit_cifar10",
                              with_xla_flops=False, end_to_end_iters=1)
        assert out["metric"] == "cifar10_vit-tiny_images_per_sec_per_chip"
        assert out["vs_baseline"] is None  # no reference number exists
        assert out["value"] > 0

    def test_lm_config(self):
        # The ONE test that keeps with_xla_flops on (AOT cost-analysis
        # cross-check) — tiniest config, so the extra compile is cheap.
        out = bench.run_lm_bench(batch_size=2, seq_len=64, timed_iters=2,
                                 with_decode=False,
                                 model_name="TransformerLM-tiny")
        assert out["metric"] == "transformer_lm_tokens_per_sec_per_chip"
        assert out["unit"] == "tokens/sec"
        assert out["value"] > 0 and np.isfinite(out["value"])

    def test_unknown_config_rejected(self):
        with pytest.raises(ValueError, match="unknown preset"):
            bench.run_bench(config="resnet9000")

    @pytest.mark.slow  # another full bench run just to read two fields
    def test_mfu_fields_present(self, monkeypatch):
        monkeypatch.delenv("TPU_DDP_PEAK_TFLOPS", raising=False)
        out = bench.run_bench(batch_size=4, timed_iters=1,
                              config="vgg11_cifar10",
                              with_xla_flops=False, end_to_end_iters=1)
        ex = out["extra"]
        # Analytic model FLOPs: VGG-11 on 32x32 is ~153M MACs fwd/img
        # (~306 MFLOPs), train = 3x fwd.
        per_img_fwd = ex["flops_per_step"] / 3 / 4
        assert 2.5e8 < per_img_fwd < 3.5e8
        assert ex["flops_source"] == "analytic"
        assert ex["achieved_tflops"] > 0
        # CPU platform: no peak table -> mfu is null, never a wrong number.
        assert ex["mfu"] is None and ex["peak_tflops_bf16"] is None

    # test_lm_config runs the same bench entry fast; this repeats it
    # only to read the peak-flops override out of the report.
    @pytest.mark.slow
    def test_mfu_env_peak_override(self, monkeypatch):
        monkeypatch.setenv("TPU_DDP_PEAK_TFLOPS", "100")
        out = bench.run_lm_bench(batch_size=2, seq_len=64, timed_iters=1,
                                 with_xla_flops=False, with_decode=False,
                                 model_name="TransformerLM-tiny")
        ex = out["extra"]
        assert ex["peak_tflops_bf16"] == 100.0
        # Both fields are rounded (3 and 4 decimals) before comparison;
        # on CPU the values are tiny, so tolerate the rounding error.
        assert ex["mfu"] == pytest.approx(
            ex["achieved_tflops"] / 100.0, abs=2e-4)

    @pytest.mark.slow  # scan-of-4 VGG compile: minutes on 1 CPU core
    def test_multi_step_recorded_for_headline(self):
        """timed_iters >= 4 triggers the scan-of-k sub-measurement on
        the headline config (k = min(16, timed_iters), so tests compile
        a short scan); its throughput field must be present/positive."""
        out = bench.run_bench(batch_size=8, timed_iters=4,
                              config="vgg11_cifar10", end_to_end_iters=1,
                              with_xla_flops=False)
        ms = out["extra"].get("multi_step")
        assert ms is not None
        assert ms["steps_per_call"] == 4
        assert ms["images_per_sec"] > 0

    @pytest.mark.slow  # decode-scan compile: minutes on 1 CPU core
    def test_lm_decode_recorded(self):
        out = bench.run_lm_bench(batch_size=2, seq_len=512, timed_iters=1)
        dec = out["extra"].get("decode")
        assert dec is not None and "error" not in dec
        assert dec["tokens_per_sec"] > 0

    def test_compact_headline_shape(self):
        """The driver parses exactly one stdout line; it must stay small
        and carry headline + MFU (round-2 truncation regression)."""
        import json
        result = {
            "metric": "cifar10_vgg11_images_per_sec_per_chip",
            "value": 72614.0, "unit": "images/sec", "vs_baseline": 188.1,
            "extra": {
                "mfu": 0.2667,
                "batch_sweep": {"2048": {"images_per_sec": 1.0,
                                         "mfu": 0.3379},
                                "4096": {"error": "OOM"}},
                "configs": {
                    "resnet50_imagenet": {"extra": {"mfu": 0.2685}},
                    "transformer_lm": {"extra": {"mfu": 0.2744}},
                    "transformer_lm_large": {"error": "boom"},
                },
            },
        }
        c = bench.compact_headline(result)
        assert c["metric"] == result["metric"]
        assert c["value"] == result["value"]
        assert c["vs_baseline"] == result["vs_baseline"]
        assert c["mfu"] == 0.2667
        # best vgg MFU comes from the sweep; best overall across families
        assert c["mfu_by_family"]["vgg11"] == 0.3379
        assert c["best_mfu"] == 0.3379
        # errors in sweep/configs never break the compact line
        line = json.dumps(c)
        assert len(line) < 1000  # must stay within driver tail capture

    def test_dispatch_depth_sweep_smoke(self):
        """The round-6 acceptance gate at smoke scale: the async window
        (depth 2) must not lose throughput to the synchronous loop
        (depth 0) and must strictly cut forced syncs and host-gap.
        Best-of-3 with a small tolerance on steps/sec — this 1-core
        host interleaves "device" compute with the host loop, so the
        wall-clock win is mostly the removed per-step sync overhead;
        the forced-sync/host-gap cuts are the deterministic claim, and
        the noise-dominated ~15ms-wall throughput ratio gets three
        sweep attempts before failing."""
        import jax.numpy as jnp

        from tpu_ddp.models.vgg import VGGModel
        from tpu_ddp.train.engine import Trainer
        from tpu_ddp.train.pipeline import depth_sweep
        from tpu_ddp.utils.config import TrainConfig

        model = VGGModel(name="tiny", cfg=(8, "M", 16, "M"),
                         compute_dtype=jnp.float32)
        trainer = Trainer(model, TrainConfig(), strategy="none")
        state = trainer.init_state()
        rng = np.random.default_rng(0)
        batches = [(rng.normal(size=(32, 4, 4, 3)).astype(np.float32),
                    rng.integers(0, 10, size=32).astype(np.int32))
                   for _ in range(10)]
        # Warm-up epoch: compile outside the timed sweep.
        state, _ = trainer.train_epoch(state, list(batches),
                                       log=lambda s: None)
        res, state = depth_sweep(trainer, state, batches, (0, 2), reps=3)
        d0, d2 = res["0"], res["2"]
        assert d2["forced_syncs"] < d0["forced_syncs"]
        assert d2["host_gap_ms"] < d0["host_gap_ms"]
        # The throughput ratio is timing noise on a shared host, so it
        # gets three sweep attempts before failing.
        attempts = [res]
        for _ in range(2):
            if d2["steps_per_sec"] >= 0.9 * d0["steps_per_sec"]:
                break
            res, state = depth_sweep(trainer, state, batches, (0, 2),
                                     reps=3)
            d0, d2 = res["0"], res["2"]
            attempts.append(res)
        assert d2["steps_per_sec"] >= 0.9 * d0["steps_per_sec"], attempts

    def test_collectives_bench_shape(self):
        out = bench.run_collectives_bench(mb=0.5, iters=2)
        # 8-device virtual mesh in tests -> real results, not skipped.
        assert out["devices"] == 8
        assert set(out["results"]) == {"psum", "psum_scatter", "all_gather",
                                       "ppermute", "all_to_all"}
        for r in out["results"].values():
            assert r["ms"] > 0 and r["gbps"] > 0
