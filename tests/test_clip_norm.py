"""Global-norm gradient clipping across layouts (round-3 verdict item 6).

torch.nn.utils.clip_grad_norm_ semantics: scale all gradients so their
GLOBAL L2 norm is at most the threshold. The norm must be the same
number in every layout — replicated (part3), ZeRO-1 dp-scattered slices
(part4), flat FSDP shards (part5), tp/sp-sharded LM grads, pipeline
stages — which these tests pin by running the SAME batch through each
layout with an aggressively small threshold (clipping always active)
and demanding identical updates. No reference counterpart (the
reference never clips, part1/main.py:124-125).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models import get_model
from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.optim import SGD, AdamW
from tpu_ddp.parallel.mesh import DATA_AXIS, make_mesh
from tpu_ddp.parallel.zero import ZeRO1
from tpu_ddp.train.engine import Trainer
from tpu_ddp.train.lm import (LMTrainer, PipelineLMTrainer,
                              make_lm_batch)
from tpu_ddp.utils.config import TrainConfig
from jax.sharding import PartitionSpec as P

CLIP = 0.05  # far below any fresh-init gradient norm: always active


def _np_clipped_sgd(params, grads, clip, lr=0.1, wd=1e-4, mom=0.9):
    """Reference implementation: numpy global-norm clip + torch-SGD."""
    norm = np.sqrt(sum(float(np.sum(np.square(g)))
                       for g in jax.tree.leaves(grads)))
    scale = min(1.0, clip / (norm + 1e-12))
    out = {}
    for k in params:
        g = grads[k] * scale + wd * params[k]
        out[k] = params[k] - lr * g  # fresh momentum buffer: buf = g
    return out, norm


class TestClipUnit:
    def test_zero1_clip_matches_numpy(self, devices):
        """ZeRO-1's slice-psum norm == the numpy full-tree norm, via the
        resulting update (momentum 0 at step 1 makes SGD linear)."""
        mesh = make_mesh(devices[:4])
        rng = np.random.default_rng(3)
        params = {"w": jnp.asarray(rng.normal(size=(8, 6)), jnp.float32),
                  "b": jnp.asarray(rng.normal(size=(7,)), jnp.float32)}
        grads = {"w": jnp.asarray(rng.normal(size=(8, 6)), jnp.float32),
                 "b": jnp.asarray(rng.normal(size=(7,)), jnp.float32)}
        zero = ZeRO1(SGD(weight_decay=1e-4), DATA_AXIS, 4)
        z_state = zero.init(params)
        spec = zero.state_specs()
        z_state = jax.device_put(z_state, jax.tree.map(
            lambda s: jax.sharding.NamedSharding(mesh, s), spec,
            is_leaf=lambda x: isinstance(x, P)))
        stepped = jax.jit(jax.shard_map(
            lambda p, g, s: zero.apply(p, g, s, clip_norm=CLIP),
            mesh=mesh, in_specs=(P(), P(), spec),
            out_specs=(P(), spec), check_vma=False))
        new_p, _ = stepped(params, grads, z_state)
        want, _ = _np_clipped_sgd(
            jax.device_get(params), jax.device_get(grads), CLIP)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(new_p[k]), want[k],
                                       rtol=1e-6, atol=1e-7, err_msg=k)

    def test_invalid_threshold_rejected(self, devices):
        mesh = make_mesh(devices[:2], dp=2)
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        with pytest.raises(ValueError, match="clip_grad_norm"):
            LMTrainer(model, mesh, clip_grad_norm=0.0)
        with pytest.raises(ValueError, match="clip_grad_norm"):
            Trainer(get_model("VGG11", compute_dtype=np.float32),
                    TrainConfig(), clip_grad_norm=-1.0)


class TestClipVGGLadder:
    """Parts 3/4/5 with clipping produce the same model: the norm is
    computed identically from replicated grads, ZeRO slices and FSDP
    shards."""

    def _step(self, devices, strategy):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 32, 32, 3)).astype(np.float32)
        y = rng.integers(0, 10, size=8).astype(np.int32)
        model = get_model("VGG11", compute_dtype=np.float32)
        tr = Trainer(model, TrainConfig(), strategy=strategy,
                     mesh=make_mesh(devices[:4]), clip_grad_norm=CLIP)
        state = tr.init_state()
        xb, yb, wb = tr.put_batch(x, y)
        for _ in range(2):
            state, loss = tr.train_step(state, xb, yb, wb)
        params = jax.device_get(state.params)
        if strategy == "fsdp":
            params = tr.zero3.unshard_host(params)
        return params, float(np.mean(np.asarray(loss)))

    @pytest.mark.slow  # 3 VGG trainers x 2 steps ~10s; the LM layout
    # agreement test below pins the same cross-layout norm algebra fast
    def test_fused_zero_fsdp_agree(self, devices):
        p_fused, l_fused = self._step(devices, "fused")
        for strategy in ("zero", "fsdp"):
            p_s, l_s = self._step(devices, strategy)
            assert abs(l_s - l_fused) < 1e-4, strategy
            for a, b in zip(jax.tree.leaves(p_fused),
                            jax.tree.leaves(p_s)):
                np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                           rtol=2e-4, atol=1e-5,
                                           err_msg=strategy)


class TestClipLM:
    """LM layouts: replicated dp == zero1 == zero2 == fsdp, and
    dp x tp == fsdp x tp, all with the clip active."""

    def _tokens(self, b=8, seed=9):
        return np.random.default_rng(seed).integers(0, 1024, size=(b, 33))

    def _run(self, devices, dp=2, sp=1, mp=1, opt_sharding="replicated",
             param_sharding="replicated", grad_accum=1, steps=2):
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:dp * sp * mp], dp=dp, sp=sp, mp=mp)
        tr = LMTrainer(model, mesh, opt_sharding=opt_sharding,
                       param_sharding=param_sharding,
                       grad_accum=grad_accum, clip_grad_norm=CLIP,
                       optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                     weight_decay=1e-4))
        state = tr.init_state(seed=11)
        x, y = tr.put_batch(*make_lm_batch(self._tokens()))
        losses = []
        for _ in range(steps):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        params = jax.device_get(state.params)
        if param_sharding == "fsdp":
            params = tr.zero3.unshard_host(params)
        return params, losses

    @pytest.mark.slow  # four LM trainer compiles; tp agreement is covered
    # fast by test_tp_layouts_agree
    def test_layouts_agree(self, devices):
        p_ref, l_ref = self._run(devices)
        variants = {
            "zero1": dict(opt_sharding="zero1"),
            "zero2": dict(opt_sharding="zero2", grad_accum=2),
            "fsdp": dict(param_sharding="fsdp"),
        }
        for name, kw in variants.items():
            p_v, l_v = self._run(devices, **kw)
            np.testing.assert_allclose(l_v, l_ref, rtol=1e-5,
                                       err_msg=name)
            for a, b in zip(jax.tree.leaves(p_ref),
                            jax.tree.leaves(p_v)):
                np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                           rtol=2e-5, atol=1e-6,
                                           err_msg=name)

    # test_layouts_agree pins the cross-layout clip agreement fast;
    # the tp mesh adds only one more layout to the same check.
    @pytest.mark.slow
    def test_tp_layouts_agree(self, devices):
        """The tp-sharded leaves' norm contribution is psum'd over mp:
        dense dp x tp == fsdp x tp == zero1 x tp."""
        p_ref, l_ref = self._run(devices, mp=2)
        for name, kw in (("fsdp", dict(param_sharding="fsdp")),
                         ("zero1", dict(opt_sharding="zero1"))):
            p_v, l_v = self._run(devices, mp=2, **kw)
            np.testing.assert_allclose(l_v, l_ref, rtol=1e-5,
                                       err_msg=name)
            for a, b in zip(jax.tree.leaves(p_ref),
                            jax.tree.leaves(p_v)):
                np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                           rtol=2e-5, atol=1e-6,
                                           err_msg=name)


class TestClipPipeline:
    """The pipeline's stage-local stacked grads contribute via a pp
    psum: pp trainer (replicated and zero1) == the dense LM trainer on
    the same tokens."""

    def _tokens(self, b=8, seed=13):
        return np.random.default_rng(seed).integers(0, 1024, size=(b, 17))

    @pytest.mark.slow  # three LM trainer compiles; the pp psum term is the
    # only new piece and tp/dense clip agreement stays fast above
    def test_pp_matches_dense(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        opt = AdamW()
        tokens = self._tokens()

        dense = LMTrainer(model, make_mesh(devices[:2], dp=2),
                          optimizer=opt, clip_grad_norm=CLIP)
        s_d = dense.init_state(seed=0)
        xd, yd = dense.put_batch(*make_lm_batch(tokens))
        losses_d = []
        for _ in range(2):
            s_d, l_d = dense.train_step(s_d, xd, yd)
            losses_d.append(float(np.mean(np.asarray(l_d))))

        from tpu_ddp.parallel.pipeline import stack_block_params
        for sharding in ("replicated", "zero1"):
            pp = PipelineLMTrainer(
                model, make_mesh(devices[:4], dp=2, pp=2), num_micro=2,
                optimizer=opt, opt_sharding=sharding,
                clip_grad_norm=CLIP)
            s_p = pp.init_state(seed=0)
            xp, yp = pp.put_batch(*make_lm_batch(tokens))
            losses_p = []
            for _ in range(2):
                s_p, l_p = pp.train_step(s_p, xp, yp)
                losses_p.append(float(np.mean(np.asarray(l_p))))
            np.testing.assert_allclose(losses_p, losses_d, rtol=1e-5,
                                       err_msg=sharding)
            want = stack_block_params(jax.device_get(s_d.params))
            got = jax.device_get(s_p.params)
            # atol 5e-6, not 1e-6: AdamW's g/sqrt(v) normalization
            # amplifies reduction-order noise where a gradient element
            # is ~0 (the test_grad_accum.py rationale) — the pipeline's
            # microbatch summation order differs from the dense step's.
            for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
                np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                           rtol=2e-5, atol=5e-6,
                                           err_msg=sharding)
