"""Text pipeline: byte tokenizer, document packing (C++ and numpy
identical), sharded epoch batches, end-to-end LM training."""

import numpy as np
import pytest

from tpu_ddp.data import text as T


DOCS = ["hello world", "the quick brown fox", "päck μe",  # utf-8 multibyte
        "a" * 100, "short"]


class TestTokenizer:
    def test_roundtrip(self):
        tok = T.ByteTokenizer()
        for s in DOCS:
            assert tok.decode(tok.encode(s)) == s

    def test_id_space(self):
        tok = T.ByteTokenizer()
        ids = tok.encode("abc")
        assert ids.min() >= T._BYTE_OFFSET
        assert ids.max() < T.VOCAB_SIZE
        assert T.VOCAB_SIZE == 259


class TestPacking:
    def test_layout(self):
        rows = T.pack_documents(["ab"], seq_len=3, add_bos=True,
                                use_native=False)
        # stream = [BOS, a, b, EOS] -> one row of 4
        a, b = 97 + 3, 98 + 3
        np.testing.assert_array_equal(rows,
                                      [[T.BOS_ID, a, b, T.EOS_ID]])

    def test_native_matches_numpy(self):
        if not T.native_available():
            pytest.skip(f"native build unavailable: {T._text_lib.build_error}")
        for add_bos in (True, False):
            got = T.pack_documents(DOCS, seq_len=16, add_bos=add_bos,
                                   use_native=True)
            want = T.pack_documents(DOCS, seq_len=16, add_bos=add_bos,
                                    use_native=False)
            np.testing.assert_array_equal(got, want)

    def test_row_shape_and_tail_drop(self):
        rows = T.pack_documents(DOCS, seq_len=16, use_native=False)
        assert rows.shape[1] == 17
        total = sum(len(d.encode()) for d in DOCS) + 2 * len(DOCS)
        assert rows.shape[0] == total // 17

    def test_too_short_raises(self):
        with pytest.raises(ValueError, match="too short"):
            T.pack_documents(["x"], seq_len=512, use_native=False)
        with pytest.raises(ValueError, match="no documents"):
            T.pack_documents([], seq_len=8)


class TestEpochBatches:
    def _rows(self, n=10, L=8):
        return np.arange(n * (L + 1), dtype=np.int32).reshape(n, L + 1)

    def test_shards_cover_all_rows(self):
        rows = self._rows(n=10)
        seen = []
        for rank in range(2):
            for x, y in T.epoch_batches(rows, 2, rank=rank, world_size=2,
                                        shuffle=False, drop_last=False):
                assert x.shape[1] == 8 and y.shape[1] == 8
                seen.extend(x[:, 0].tolist())
        # 10 rows over 2 ranks, wrap-padded evenly: every row appears.
        assert set(seen) >= set(rows[:, 0].tolist())

    def test_shuffle_varies_by_epoch_and_agrees_across_ranks(self):
        rows = self._rows(n=8)
        def first_tokens(rank, epoch):
            return [x[0, 0] for x, _ in T.epoch_batches(
                rows, 1, rank=rank, world_size=2, seed=7, epoch=epoch)]
        assert first_tokens(0, 0) != first_tokens(0, 1)
        # Shared seed: rank shards are disjoint within an epoch.
        assert not (set(first_tokens(0, 0)) & set(first_tokens(1, 0)))

    def test_pad_exceeding_rows(self):
        """1 row over 4 ranks: every rank still gets one full batch
        (wrap-tiled), so collective loops stay in lockstep."""
        rows = self._rows(n=1)
        counts = [sum(1 for _ in T.epoch_batches(
            rows, 1, rank=r, world_size=4, shuffle=False))
            for r in range(4)]
        assert counts == [1, 1, 1, 1]

    def test_targets_are_shifted_inputs(self):
        rows = self._rows(n=4)
        for x, y in T.epoch_batches(rows, 2, shuffle=False):
            np.testing.assert_array_equal(x[:, 1:], y[:, :-1])


class TestEndToEnd:
    def test_lm_trains_on_packed_text(self, devices):
        import jax
        import jax.numpy as jnp
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer

        tok = T.ByteTokenizer()
        docs = ["the cat sat on the mat. " * 8] * 12
        rows = T.pack_documents(docs, seq_len=32)
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 vocab_size=tok.vocab_size,
                                 compute_dtype=jnp.float32)
        tr = LMTrainer(model, make_mesh(devices[:2], dp=2))
        state = tr.init_state(seed=0)
        losses = []
        for epoch in range(2):
            for inp, tgt in T.epoch_batches(rows, 4, seed=1, epoch=epoch):
                x, y = tr.put_batch(inp, tgt)
                state, loss = tr.train_step(state, x, y)
                losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # byte-level repetition memorizes
