"""C++ native data pipeline vs the numpy pipeline.

The native loader replaces torchvision transforms + DataLoader workers
(SURVEY.md §2 row N4). These tests pin its contract: exact normalization
parity, deterministic schedule-independent augmentation, DataLoader-equal
iteration shape, and sharded operation.
"""

import numpy as np
import pytest

from tpu_ddp.data import native
from tpu_ddp.data.cifar10 import normalize
from tpu_ddp.data.loader import DataLoader, create_data_loaders
from tpu_ddp.data.sampler import DistributedShardSampler

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native library unavailable: {native.build_error()}")


def _toy(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


class TestTransformBatch:
    def test_normalize_matches_numpy(self):
        x, y = _toy()
        out_x, out_y = native.transform_batch(x, y, augment=False)
        np.testing.assert_allclose(out_x, normalize(x), rtol=0, atol=1e-6)
        np.testing.assert_array_equal(out_y, y)

    def test_indices_select(self):
        x, y = _toy()
        idx = np.array([5, 3, 3, 60], dtype=np.int64)
        out_x, out_y = native.transform_batch(x, y, idx, augment=False)
        np.testing.assert_allclose(out_x, normalize(x[idx]), atol=1e-6)
        np.testing.assert_array_equal(out_y, y[idx])

    def test_augment_deterministic_and_epoch_varying(self):
        x, y = _toy()
        a1, _ = native.transform_batch(x, y, augment=True, seed=1, epoch=0)
        a2, _ = native.transform_batch(x, y, augment=True, seed=1, epoch=0)
        b, _ = native.transform_batch(x, y, augment=True, seed=1, epoch=1)
        np.testing.assert_array_equal(a1, a2)
        assert np.abs(a1 - b).max() > 0  # some image moved

    def test_augment_is_crop_of_padded(self):
        """Every augmented image must be a 32x32 window of the 40x40
        zero-padded (possibly flipped) original."""
        x, y = _toy(n=4)
        out, _ = native.transform_batch(x, y, augment=True, seed=7)
        x_norm_pad = np.zeros((4, 40, 40, 3), np.float32)
        x_norm_pad += normalize(np.zeros((1, 1, 1, 3), np.uint8))  # pad value
        x_norm_pad[:, 4:36, 4:36] = normalize(x)
        for i in range(4):
            found = False
            for dy in range(9):
                for dx in range(9):
                    win = x_norm_pad[i, dy:dy + 32, dx:dx + 32]
                    if np.allclose(out[i], win, atol=1e-6) or \
                       np.allclose(out[i], win[:, ::-1], atol=1e-6):
                        found = True
                        break
                if found:
                    break
            assert found, f"image {i} is not a crop/flip of its original"


class TestNativeDataLoader:
    def test_matches_python_loader_no_augment(self):
        x, y = _toy(n=70)
        py = DataLoader(x, y, batch_size=32, augment=False)
        nat = native.NativeDataLoader(x, y, batch_size=32, augment=False)
        assert len(py) == len(nat) == 3
        for (px, pl_), (nx, nl) in zip(py, nat):
            np.testing.assert_allclose(nx, px, atol=1e-6)
            np.testing.assert_array_equal(nl, pl_)

    def test_short_final_batch_kept(self):
        x, y = _toy(n=70)
        sizes = [len(l) for _, l in
                 native.NativeDataLoader(x, y, batch_size=32)]
        assert sizes == [32, 32, 6]  # drop_last=False

    def test_sharded(self):
        x, y = _toy(n=64)
        shards = []
        for rank in range(4):
            s = DistributedShardSampler(64, num_replicas=4, rank=rank,
                                        shuffle=False, drop_last=False)
            loader = native.NativeDataLoader(x, y, batch_size=16,
                                             sampler=s, augment=False)
            shards.append(np.concatenate([l for _, l in loader]))
        # All 64 labels covered exactly once across the 4 ranks.
        assert sorted(np.concatenate(shards).tolist()) == sorted(y.tolist())

    def test_deterministic_across_runs_with_augment(self):
        x, y = _toy(n=40)
        def run():
            loader = native.NativeDataLoader(x, y, batch_size=16,
                                             augment=True, seed=3,
                                             num_threads=3)
            loader.set_epoch(2)
            return np.concatenate([b for b, _ in loader])
        np.testing.assert_array_equal(run(), run())

    def test_multiple_epochs_reiterable(self):
        x, y = _toy(n=20)
        loader = native.NativeDataLoader(x, y, batch_size=8, augment=True)
        n0 = sum(len(l) for _, l in loader)
        loader.set_epoch(1)
        n1 = sum(len(l) for _, l in loader)
        assert n0 == n1 == 20

    def test_create_data_loaders_native_flag(self):
        tr, te = create_data_loaders(batch_size=16, synthetic_size=64,
                                     native=True)
        assert isinstance(tr, native.NativeDataLoader)
        xb, yb = next(iter(tr))
        assert xb.shape == (16, 32, 32, 3) and xb.dtype == np.float32
        assert isinstance(te, native.NativeDataLoader)
