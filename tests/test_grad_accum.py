"""Gradient accumulation (microbatching) in the LM engine.

The decisive property: a step with grad_accum=A on batch B produces the
SAME parameter update as one plain step on the full batch — accumulation
is a memory lever, not a different optimizer. (No reference counterpart:
its fixed global batch of 256 needs no splitting — SURVEY.md §5.)
"""

import jax
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.train.lm import LMTrainer, make_lm_batch

import jax.numpy as jnp


def _model():
    return make_transformer("TransformerLM-tiny", max_seq_len=32,
                            compute_dtype=jnp.float32)


def _tokens(b=8):
    rng = np.random.default_rng(5)
    return rng.integers(0, 1024, size=(b, 33))


def _step(devices, grad_accum, param_sharding="replicated", dp=2, sp=1):
    # SGD, not AdamW: the update is LINEAR in the gradient, so the
    # accumulated and single-shot steps must agree to fp-roundoff — AdamW's
    # g/sqrt(v) normalization amplifies harmless summation-order noise
    # unboundedly wherever a gradient element is ~0.
    from tpu_ddp.ops.optim import SGD
    mesh = make_mesh(devices[:dp * sp], dp=dp, sp=sp)
    tr = LMTrainer(_model(), mesh, grad_accum=grad_accum,
                   param_sharding=param_sharding,
                   optimizer=SGD(learning_rate=0.1, momentum=0.9,
                                 weight_decay=1e-4))
    state = tr.init_state(seed=21)
    x, y = tr.put_batch(*make_lm_batch(_tokens()))
    state, loss = tr.train_step(state, x, y)
    params = jax.device_get(state.params)
    if param_sharding == "fsdp":
        params = tr.zero3.unshard_host(params)
    return params, float(np.mean(np.asarray(loss)))


class TestGradAccum:
    @pytest.mark.parametrize("accum", [
        # accum=4 only lengthens the scan accum=2 already pins.
        2, pytest.param(4, marks=pytest.mark.slow)])
    def test_matches_single_step(self, devices, accum):
        p1, l1 = _step(devices, 1)
        pa, la = _step(devices, accum)
        assert abs(l1 - la) < 1e-5
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(pa)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6)

    def test_matches_under_fsdp(self, devices):
        p1, l1 = _step(devices, 1, param_sharding="fsdp")
        pa, la = _step(devices, 2, param_sharding="fsdp")
        assert abs(l1 - la) < 1e-5
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(pa)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6)

    @pytest.mark.slow  # accum x sp adds only layout on the loop the
    # plain parity above pins fast (accum=2); fsdp composition stays
    # fast as the one sharded representative.
    def test_matches_under_sp(self, devices):
        p1, l1 = _step(devices, 1, dp=2, sp=2)
        pa, la = _step(devices, 2, dp=2, sp=2)
        assert abs(l1 - la) < 1e-5
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(pa)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=1e-6)

    def test_divisibility_enforced(self, devices):
        mesh = make_mesh(devices[:2], dp=2)
        tr = LMTrainer(_model(), mesh, grad_accum=3)
        with pytest.raises(ValueError, match="grad_accum"):
            tr.put_batch(*make_lm_batch(_tokens(b=8)))

    def test_invalid_accum_rejected(self, devices):
        mesh = make_mesh(devices[:2], dp=2)
        with pytest.raises(ValueError, match="grad_accum"):
            LMTrainer(_model(), mesh, grad_accum=0)
