"""Fleet resilience (tpu_ddp/fleet/resilience.py, docs/DESIGN.md §23):
replica health + deterministic migration in the Router, degraded-mode
disaggregation, SLO-aware load shedding, and the serve-side chaos
kinds.

The acceptance bar is the same one the fleet was built on — BITWISE
TOKEN PARITY — now under faults: a replica crash mid-decode, a dropped
KV-edge delivery, or a dead prefill worker must leave the surviving
token streams identical to the undisturbed run (sampling is stateless
keyed on (seed, position), so a migrated continuation replayed from
``prompt + tokens_so_far`` re-keys exactly where the original left
off). On top of parity, every drill pins the accounting identity:
``completed + cancelled + shed == submitted`` — no request is ever
lost, resurrected after cancel, or double-freed.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.fleet import (
    DisaggEngine,
    ReplicaCrashError,
    ReplicaHealth,
    Router,
    continuation_of,
)
from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.serve import ServeEngine, make_workload, run_load

GEOM = dict(num_slots=4, block_size=8, prefill_chunk=8)

MIXED = [(0, 5, 6, 0.0), (1, 9, 5, 0.0), (2, 12, 4, 0.7),
         (3, 8, 6, 1.0)]


@pytest.fixture(scope="module")
def model():
    return make_transformer("TransformerLM-tiny", max_seq_len=64,
                            compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


@pytest.fixture(scope="module")
def baseline(model, params):
    """Undisturbed single-engine token streams for MIXED — the parity
    reference every fault drill is judged against."""
    eng = ServeEngine(model, params, **GEOM)
    hs = _submit_mixed(eng)
    eng.run()
    return [list(h.tokens) for h in hs]


def _prompt(L, seed=0):
    return np.random.default_rng(seed).integers(0, 1024, size=L,
                                                dtype=np.int64)


def _submit_mixed(engine):
    return [engine.submit(_prompt(L, seed=ps), n, temperature=t, seed=i)
            for i, (ps, L, n, t) in enumerate(MIXED)]


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _Crashy:
    """Replica wrapper that raises out of step() exactly once at the
    Nth step — the deterministic stand-in for a replica crash."""

    def __init__(self, engine, crash_at):
        self.engine = engine
        self.crash_at = crash_at
        self.n = 0

    def step(self):
        self.n += 1
        if self.n == self.crash_at:
            raise ReplicaCrashError(f"synthetic crash at step {self.n}")
        return self.engine.step()

    def __getattr__(self, name):
        return getattr(self.engine, name)


class TestReplicaHealth:
    def test_backoff_doubles_and_caps(self):
        clk = _FakeClock()
        h = ReplicaHealth(backoff_s=0.2, backoff_cap_s=1.0, clock=clk)
        assert h.healthy
        assert h.mark_failure() == pytest.approx(0.2)
        assert h.mark_failure() == pytest.approx(0.4)
        assert h.mark_failure() == pytest.approx(0.8)
        assert h.mark_failure() == pytest.approx(1.0)   # capped
        assert h.mark_failure() == pytest.approx(1.0)
        assert not h.healthy and h.failures == 5

    def test_probe_gate_and_recovery_reset(self):
        clk = _FakeClock()
        h = ReplicaHealth(backoff_s=0.5, clock=clk)
        h.mark_failure()
        assert not h.probe_due()          # backoff not served yet
        clk.t = 0.49
        assert not h.probe_due()
        clk.t = 0.5
        assert h.probe_due()
        h.mark_recovered()
        assert h.healthy and h.failures == 0
        # Post-recovery failure starts the schedule over at 1x.
        assert h.mark_failure() == pytest.approx(0.5)

    def test_rejects_nonpositive_backoff(self):
        with pytest.raises(ValueError, match="backoff_s"):
            ReplicaHealth(backoff_s=0.0)


class TestContinuation:
    def test_prompt_extends_and_budget_shrinks(self, model, params):
        eng = ServeEngine(model, params, **GEOM)
        h = eng.submit(_prompt(6, seed=1), 5, seed=3)
        eng.run()
        assert len(h.tokens) == 5
        prompt, budget = continuation_of(h)
        assert budget == 0
        np.testing.assert_array_equal(
            prompt, np.concatenate([np.asarray(h.prompt, np.int32),
                                    np.asarray(h.tokens, np.int32)]))

    def test_tokenless_request_passes_through(self, model, params):
        eng = ServeEngine(model, params, **GEOM)
        h = eng.submit(_prompt(6, seed=1), 5)
        prompt, budget = continuation_of(h)
        assert budget == 5 and len(prompt) == 6


class TestMigration:
    def test_crash_mid_decode_is_bitwise_invisible(self, model, params,
                                                   baseline):
        """The tentpole contract: a replica dying mid-decode migrates
        its in-flight requests, and the final token streams are
        IDENTICAL to the undisturbed single-engine run."""
        crashy = _Crashy(ServeEngine(model, params, **GEOM), crash_at=4)
        other = ServeEngine(model, params, **GEOM)
        router = Router([crashy, other], probe_backoff_ms=10_000.0)
        hs = _submit_mixed(router)
        with pytest.warns(UserWarning, match="marked unhealthy"):
            router.run()
        assert all(h.done for h in hs)
        assert [list(h.tokens) for h in hs] == baseline
        st = router.stats()
        assert st["failovers"] == 1
        assert st["migrated"] + st["retried"] >= 1
        assert router.accounting_ok()

    def test_backoff_probe_readmits_the_replica(self, model, params,
                                                baseline):
        crashy = _Crashy(ServeEngine(model, params, **GEOM), crash_at=2)
        other = ServeEngine(model, params, **GEOM)
        router = Router([crashy, other], probe_backoff_ms=1.0)
        hs = _submit_mixed(router)
        with pytest.warns(UserWarning, match="marked unhealthy"):
            router.run()
        assert [list(h.tokens) for h in hs] == baseline
        # The 1ms backoff elapses inside the run: the probe step
        # succeeds (the crash is one-shot) and the replica rejoins.
        assert router.stats()["readmitted"] == 1
        assert all(h.healthy for h in router.health)
        # The re-admitted replica serves new traffic bitwise-correctly.
        hs2 = _submit_mixed(router)
        router.run()
        assert [list(h.tokens) for h in hs2] == baseline
        assert router.accounting_ok()

    def test_whole_fleet_dark_holds_then_replays(self, model, params,
                                                 baseline):
        crashy = _Crashy(ServeEngine(model, params, **GEOM), crash_at=1)
        router = Router([crashy], probe_backoff_ms=1.0)
        with pytest.warns(UserWarning, match="marked unhealthy"):
            router.step()                      # kill the only replica
        hs = _submit_mixed(router)             # fleet dark: held
        assert router.stats()["pending"] == 4
        router.run()
        assert all(h.done for h in hs)
        assert [list(h.tokens) for h in hs] == baseline
        assert router.accounting_ok()

    def test_retry_budget_exhaustion_sheds(self, model, params):
        crashy = _Crashy(ServeEngine(model, params, **GEOM), crash_at=4)
        router = Router([crashy], retry_budget=0,
                        probe_backoff_ms=1.0)
        hs = _submit_mixed(router)
        with pytest.warns(UserWarning, match="marked unhealthy"):
            router.run()
        assert all(h.done for h in hs)
        shed = [h for h in hs if h.shed]
        assert shed and router.stats()["shed"] == len(shed)
        done = sum(not h.shed and not h.cancelled for h in hs)
        assert done + len(shed) == len(hs)     # the identity
        assert router.accounting_ok()


class TestCancelDuringMigration:
    def test_cancel_in_pending_queue_never_resurrects(self, model,
                                                      params):
        """The regression the satellite pins: cancelling a request
        parked in the retry queue (its pages already freed by the
        failover drain) must neither resurrect it at the next resubmit
        nor double-free anything."""
        crashy = _Crashy(ServeEngine(model, params, **GEOM), crash_at=3)
        other = _Crashy(ServeEngine(model, params, **GEOM), crash_at=3)
        router = Router([crashy, other], probe_backoff_ms=1.0)
        hs = _submit_mixed(router)
        with pytest.warns(UserWarning, match="marked unhealthy"):
            while not router.stats()["pending"]:
                router.step()                  # both replicas die
        victim = next(h for h in hs
                      if any(p is h for p in router._pending))
        assert router.cancel(victim) is True
        assert victim.cancelled and victim.done
        ntoks = len(victim.tokens)
        router.run()                           # replays the survivors
        assert all(h.done for h in hs)
        assert not any(h is victim for _, c, _, _
                       in router._migrating.values()
                       for h in (c,))          # never resubmitted
        assert len(victim.tokens) == ntoks     # no zombie tokens
        # Double-cancel is a no-op, and pool accounting still balances
        # on every replica (a double-free would throw or break it).
        assert router.cancel(victim) is False
        assert router.accounting_ok()
        done = sum(not h.cancelled for h in hs)
        assert done + 1 == len(hs)

    def test_cancel_of_migrating_continuation(self, model, params):
        crashy = _Crashy(ServeEngine(model, params, **GEOM), crash_at=4)
        other = ServeEngine(model, params, **GEOM)
        router = Router([crashy, other], probe_backoff_ms=10_000.0)
        hs = _submit_mixed(router)
        with pytest.warns(UserWarning, match="marked unhealthy"):
            while not router._migrating:
                router.step()
        victim = next(h for h in hs if id(h) in router._migrating)
        assert router.cancel(victim) is True
        assert victim.cancelled and id(victim) not in router._migrating
        router.run()
        assert all(h.done for h in hs)
        assert router.accounting_ok()


class TestDegradedDisagg:
    def test_edge_drop_falls_back_to_local_prefill(self, model, params,
                                                   baseline,
                                                   monkeypatch):
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS", "edge-drop@2")
        fleet = DisaggEngine(model, params, **GEOM)
        assert fleet.chaos is not None
        hs = _submit_mixed(fleet)
        with pytest.warns(UserWarning, match="lost on the edge"):
            fleet.run()
        assert all(h.done for h in hs)
        assert [list(h.tokens) for h in hs] == baseline
        assert fleet.metrics.counters.get("fleet_edge_failures") == 1
        assert fleet.edge.dropped == 1
        assert fleet.accounting_ok()

    def test_prefill_death_degrades_engine_to_local(self, model,
                                                    params, baseline):
        fleet = DisaggEngine(model, params, **GEOM)
        calls = {"n": 0}
        orig = fleet._prefill

        def dying(*a, **kw):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("prefill worker died")
            return orig(*a, **kw)

        fleet._prefill = dying
        hs = _submit_mixed(fleet)
        with pytest.warns(UserWarning,
                          match="falling back to local chunked"):
            fleet.run()
        assert fleet.prefill_degraded
        assert all(h.done for h in hs)
        assert [list(h.tokens) for h in hs] == baseline
        assert fleet.accounting_ok()
        # Degraded mode is sticky: later submits take the local path
        # and still match the reference bitwise.
        hs2 = _submit_mixed(fleet)
        fleet.run()
        assert [list(h.tokens) for h in hs2] == baseline
        assert fleet.accounting_ok()


class TestQuarantine:
    def test_poisoned_request_is_quarantined_not_the_batch(
            self, model, params, baseline, monkeypatch):
        """The decode analog of StepGuard: NaN'd KV pages make exactly
        one request's logits non-finite; the in-graph finiteness mask
        quarantines THAT request while its batchmates keep their
        bitwise-exact streams."""
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS", "nonfinite-logits@6")
        eng = ServeEngine(model, params, **GEOM)
        hs = _submit_mixed(eng)
        with pytest.warns(UserWarning, match="quarantin"):
            eng.run()
        assert all(h.done for h in hs)
        bad = [h for h in hs if h.quarantined]
        assert len(bad) == 1
        assert [list(h.tokens) for h in hs if not h.quarantined] \
            == [b for h, b in zip(hs, baseline) if not h.quarantined]
        assert eng.metrics.counters.get("serve_quarantined") == 1
        assert eng.accounting_ok()
        # The poisoned pages were scrubbed before refill: reusing the
        # pool must produce finite, bitwise-correct streams.
        monkeypatch.delenv("TPU_DDP_CHAOS_FAULTS")
        hs2 = _submit_mixed(eng)
        eng.run()
        assert [list(h.tokens) for h in hs2] == baseline


class TestLoadShedding:
    def test_queue_limit_sheds_at_the_door(self, model, params):
        eng = ServeEngine(model, params, queue_limit=1, **GEOM)
        hs = _submit_mixed(eng)
        hs += [eng.submit(_prompt(6, seed=9), 4, seed=9)
               for _ in range(4)]
        eng.run()
        n_shed = sum(h.shed for h in hs)
        n_done = sum(h.done and not h.shed for h in hs)
        assert n_shed >= 1
        for h in hs:
            if h.shed:
                assert h.done and not h.tokens
        assert n_shed + n_done == len(hs)      # the identity
        assert eng.metrics.counters.get("serve_shed") == n_shed
        assert eng.accounting_ok()

    def test_deadline_shed_drops_stale_queue_entries(self, model,
                                                     params):
        clockbox = {"t": 0.0}
        eng = ServeEngine(model, params, shed_ms=50.0, **GEOM)
        hs = [eng.submit(_prompt(5, seed=s), 3, seed=s)
              for s in range(8)]
        # Age the queued (not yet prefilled) tail past the deadline.
        for h in hs:
            if not h.tokens:
                h.submitted_at -= 10.0
        eng.run()
        assert all(h.done for h in hs)
        assert any(h.shed for h in hs)
        assert sum(h.shed for h in hs) \
            + sum(not h.shed and not h.cancelled for h in hs) == len(hs)
        assert eng.accounting_ok()
        del clockbox

    def test_run_load_accounts_shed_honestly(self, model, params):
        specs = make_workload(12, vocab_size=1024, seed=0,
                              prompt_len=(4, 9), max_new=(3, 6))
        eng = ServeEngine(model, params, queue_limit=1, **GEOM)
        m = run_load(eng, specs, rate=10_000.0, seed=1,
                     slo_ttft_ms=50.0)
        assert m["accounting_ok"]
        assert m["n_completed"] + m["n_cancelled"] + m["n_shed"] \
            == m["n_requests"]
        assert m["n_shed"] >= 1
        # Goodput and percentiles are over completed requests only; a
        # 100%-shed run must report None, not crash.
        assert m["total_tokens"] >= 0

    def test_negative_knobs_rejected(self, model, params):
        with pytest.raises(ValueError, match="queue_limit"):
            ServeEngine(model, params, queue_limit=-1, **GEOM)
        with pytest.raises(ValueError, match="shed_ms"):
            ServeEngine(model, params, shed_ms=-0.5, **GEOM)


class TestChaosSpecs:
    def test_serve_kinds_parse_and_train_kinds_ignored(self,
                                                       monkeypatch):
        from tpu_ddp.resilience.chaos import SERVE_FAULT_KINDS, FaultSpec
        for kind in SERVE_FAULT_KINDS:
            # tenant-storm is the one kind scoped to a tenant; it
            # refuses to parse without one (DESIGN.md §25).
            tenant = "gold" if kind == "tenant-storm" else None
            FaultSpec(kind=kind, step=3, tenant=tenant)
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultSpec(kind="replica-typo", step=3)
        # A mixed train+serve spec string: the serve injector ignores
        # the training kind entirely.
        from tpu_ddp.fleet.resilience import ServeFaultInjector
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS",
                           "nan-grad@3,replica-crash@5:rank=1")
        inj = ServeFaultInjector.from_env()
        inj.set_rank(0)
        for s in range(1, 10):
            inj.replica_step(s)               # rank mismatch: no fire
        inj.set_rank(1)
        with pytest.raises(ReplicaCrashError):
            inj.replica_step(5)

    def test_crash_is_one_shot_as_steps_advance(self, monkeypatch):
        # One-shot comes from the exact step match: the engine's step
        # counter keeps advancing through the crash, so the probe that
        # re-admits the replica (a LATER step) never re-fires it.
        from tpu_ddp.fleet.resilience import ServeFaultInjector
        monkeypatch.setenv("TPU_DDP_CHAOS_FAULTS", "replica-crash@2")
        inj = ServeFaultInjector.from_env()
        with pytest.raises(ReplicaCrashError):
            inj.replica_step(2)
        for s in range(3, 8):
            inj.replica_step(s)               # silent forever after


class TestKnobSurfaces:
    @pytest.mark.parametrize("env,junk", [
        ("TPU_DDP_FLEET_HEALTH_BACKOFF_MS", "fast"),
        ("TPU_DDP_FLEET_HEALTH_BACKOFF_MS", "0"),      # must be > 0
        ("TPU_DDP_FLEET_HEALTH_DEADLINE_MS", "soon"),
        ("TPU_DDP_FLEET_HEALTH_DEADLINE_MS", "-1"),
        ("TPU_DDP_FLEET_RETRY_BUDGET", "many"),
        ("TPU_DDP_FLEET_RETRY_BUDGET", "-2"),
        ("TPU_DDP_SERVE_QUEUE_LIMIT", "big"),
        ("TPU_DDP_SERVE_QUEUE_LIMIT", "-1"),
        ("TPU_DDP_SERVE_SHED_MS", "never"),
        ("TPU_DDP_SERVE_SHED_MS", "-3"),
    ])
    def test_env_surface_rejects_junk(self, env, junk, monkeypatch):
        from tpu_ddp.utils.config import TrainConfig
        monkeypatch.setenv(env, junk)
        with pytest.raises(ValueError, match=env):
            TrainConfig()

    def test_env_surface_parses_good_values(self, monkeypatch):
        from tpu_ddp.utils.config import TrainConfig
        monkeypatch.setenv("TPU_DDP_FLEET_HEALTH", "0")
        monkeypatch.setenv("TPU_DDP_FLEET_HEALTH_BACKOFF_MS", "50")
        monkeypatch.setenv("TPU_DDP_FLEET_HEALTH_DEADLINE_MS", "250")
        monkeypatch.setenv("TPU_DDP_FLEET_RETRY_BUDGET", "1")
        monkeypatch.setenv("TPU_DDP_SERVE_QUEUE_LIMIT", "64")
        monkeypatch.setenv("TPU_DDP_SERVE_SHED_MS", "100")
        cfg = TrainConfig()
        assert cfg.fleet_health is False
        assert cfg.fleet_probe_backoff_ms == 50.0
        assert cfg.fleet_step_deadline_ms == 250.0
        assert cfg.fleet_retry_budget == 1
        assert cfg.serve_queue_limit == 64
        assert cfg.serve_shed_ms == 100.0

    def test_router_reads_config_knobs(self, model, params,
                                        monkeypatch):
        monkeypatch.setenv("TPU_DDP_FLEET_HEALTH", "0")
        router = Router([ServeEngine(model, params, **GEOM)])
        assert router.health_enabled is False
        # Health off = fail-fast: the exception propagates.
        crashy = _Crashy(ServeEngine(model, params, **GEOM), crash_at=1)
        router = Router([crashy], health=False)
        crashy.engine.submit(_prompt(5), 2)
        with pytest.raises(ReplicaCrashError):
            router.run()
