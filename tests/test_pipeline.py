"""Pipeline parallelism: the staged model computes EXACTLY the same
function — loss, gradients, one full optimizer step — as the dense model,
alone and composed with dp and tp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.parallel.pipeline import (stack_block_params,
                                       unstack_block_params)
from tpu_ddp.train.lm import (LMTrainer, PipelineLMTrainer, make_lm_batch)


def _tiny(**kw):
    cfg = dict(max_seq_len=32, compute_dtype=jnp.float32, num_layers=4)
    cfg.update(kw)
    return make_transformer("TransformerLM-tiny", **cfg)


def _tokens(b=4, L=33, seed=5):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 1024, size=(b, L))


class TestStacking:
    def test_roundtrip(self):
        model = _tiny()
        params = model.init(jax.random.key(0))
        stacked = stack_block_params(params)
        assert stacked["blocks"]["wqkv"].shape[0] == model.num_layers
        back = unstack_block_params(stacked, model.num_layers)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _sgd():
    # SGD's update is LINEAR in the gradient, so tiny psum-reordering
    # noise stays tiny in the params; AdamW's first step is ~lr*sign(g),
    # which would amplify a near-zero gradient's sign flip to 2*lr.
    from tpu_ddp.ops.optim import SGD
    return SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)


class TestPipelineEquivalence:
    def _dense_step(self, devices, tokens):
        model = _tiny()
        tr = LMTrainer(model, make_mesh(devices[:1], dp=1),
                       optimizer=_sgd())
        state = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, loss = tr.train_step(state, x, y)
        return (jax.device_get(state.params),
                float(np.mean(np.asarray(loss))))

    # Two representative cells run in the default tier (basic gpipe,
    # basic 1f1b); the rest of the grid — including the tp/dp crosses —
    # is `slow` (round-3: the default tier must fit the 1-core CI
    # budget; round-16 trimmed the crosses, which the interleaved /
    # zero-bubble grid below still exercises fast).
    _slow = pytest.mark.slow
    @pytest.mark.parametrize("dp,pp,tp,micro,schedule", [
        # gpipe is the degenerate (no-overlap) schedule of the 1f1b
        # cell kept fast below; all gpipe grids ride the slow tier.
        pytest.param(1, 2, 1, 2, "gpipe", marks=_slow),
        pytest.param(1, 4, 1, 4, "gpipe", marks=_slow),
        pytest.param(2, 2, 1, 2, "gpipe", marks=_slow),
        pytest.param(1, 2, 2, 2, "gpipe", marks=_slow),
        # single microbatch: pure bubble, exact
        pytest.param(1, 4, 1, 1, "gpipe", marks=_slow),
        (1, 2, 1, 4, "1f1b"),
        pytest.param(1, 4, 1, 4, "1f1b", marks=_slow),
        pytest.param(2, 2, 1, 2, "1f1b", marks=_slow),
        pytest.param(1, 2, 2, 2, "1f1b", marks=_slow),
        # M < pp: drains correctly
        pytest.param(1, 2, 1, 1, "1f1b", marks=_slow),
    ])
    def test_one_step_matches_dense(self, devices, dp, pp, tp, micro,
                                    schedule):
        tokens = _tokens()
        dense_p, dense_loss = self._dense_step(devices, tokens)

        model = _tiny()
        mesh = make_mesh(devices[:dp * pp * tp], dp=dp, sp=1, mp=tp, pp=pp)
        tr = PipelineLMTrainer(model, mesh, num_micro=micro,
                               optimizer=_sgd(), schedule=schedule)
        state = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, loss = tr.train_step(state, x, y)
        got_loss = float(np.mean(np.asarray(loss)))
        assert abs(got_loss - dense_loss) < 1e-4, (dp, pp, tp, micro)

        got = unstack_block_params(jax.device_get(state.params),
                                   model.num_layers)
        for a, b in zip(jax.tree.leaves(dense_p), jax.tree.leaves(got)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=3e-4, atol=3e-5,
                err_msg=f"dp={dp} pp={pp} tp={tp} micro={micro}")

    @pytest.mark.parametrize("dp,sp,schedule,sp_mode", [
        # the 2x2 1f1b cell exercises dp x sp x pp in one program; the
        # 1x2 gpipe cell adds only the other schedule at another layout
        pytest.param(1, 2, "gpipe", "ring", marks=_slow),
        (2, 2, "1f1b", "ring"),
        pytest.param(1, 2, "gpipe", "ulysses", marks=_slow),
        pytest.param(1, 4, "1f1b", "ring", marks=_slow),
    ])
    def test_sp_composition_matches_dense(self, devices, dp, sp,
                                          schedule, sp_mode):
        """pp x sp (round 4): ring/Ulysses attention inside the pipeline
        stages — one step equals the dense single-device step."""
        tokens = _tokens()
        dense_p, dense_loss = self._dense_step(devices, tokens)

        model = _tiny()
        mesh = make_mesh(devices[:dp * sp * 2], dp=dp, sp=sp, pp=2)
        tr = PipelineLMTrainer(model, mesh, num_micro=2,
                               optimizer=_sgd(), schedule=schedule,
                               sp_mode=sp_mode)
        state = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, loss = tr.train_step(state, x, y)
        got_loss = float(np.mean(np.asarray(loss)))
        # Tolerances one notch wider than the sp=1 cells: the sp chunks'
        # ring-attention collectives + the microbatch scheduling give a
        # genuinely different f32 reduction order than the dense step,
        # and XLA:CPU's run-to-run scheduling makes the residual itself
        # jitter at the old 3e-4/1e-4 boundary (observed ~1-in-5 flake).
        assert abs(got_loss - dense_loss) < 3e-4, (dp, sp, schedule)

        got = unstack_block_params(jax.device_get(state.params),
                                   model.num_layers)
        for a, b in zip(jax.tree.leaves(dense_p), jax.tree.leaves(got)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=5e-4, atol=5e-5,
                err_msg=f"dp={dp} sp={sp} {schedule} {sp_mode}")

    @pytest.mark.slow  # pp + dense AdamW double compile pinning one mask
    # property; the step-equivalence tests above exercise the same path
    def test_adamw_decay_mask_uses_original_ranks(self, devices):
        """Stacking raises LN scales/biases to rank 2; AdamW must still
        exempt them from weight decay (regression: a pipelined AdamW step
        must equal the dense AdamW step on LN leaves, where a spuriously
        applied decay of wd*1.0 would dominate the tiny gradient)."""
        tokens = _tokens()
        model = _tiny()
        dense = LMTrainer(model, make_mesh(devices[:1], dp=1))
        ds = dense.init_state(seed=7)
        x, y = dense.put_batch(*make_lm_batch(tokens))
        ds, _ = dense.train_step(ds, x, y)
        dense_ln = np.asarray(
            jax.device_get(ds.params)["blocks"][0]["ln1"]["scale"])

        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
        tr = PipelineLMTrainer(model, mesh, num_micro=2)
        ps = tr.init_state(seed=7)
        xp, yp = tr.put_batch(*make_lm_batch(tokens))
        ps, _ = tr.train_step(ps, xp, yp)
        pipe_ln = np.asarray(jax.device_get(
            unstack_block_params(ps.params, model.num_layers)
        )["blocks"][0]["ln1"]["scale"])
        np.testing.assert_allclose(pipe_ln, dense_ln, rtol=1e-4,
                                   atol=1e-6)

    @pytest.mark.slow  # two dropout-pp compiles; pp dropout geometry is
    # also pinned fast by test_dropout's pipeline invariant
    def test_1f1b_matches_gpipe_with_dropout(self, devices):
        """The two schedules draw IDENTICAL dropout masks (keys derive
        from (microbatch, global layer), independent of the schedule), so
        their one-step results must agree with dropout active."""
        tokens = _tokens()
        results = {}
        for schedule in ("gpipe", "1f1b"):
            model = _tiny(dropout_rate=0.3)
            mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
            tr = PipelineLMTrainer(model, mesh, num_micro=4,
                                   optimizer=_sgd(), schedule=schedule,
                                   dropout_seed=3)
            state = tr.init_state(seed=7)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            state, loss = tr.train_step(state, x, y)
            results[schedule] = (float(np.mean(np.asarray(loss))),
                                 jax.device_get(state.params))
        assert abs(results["gpipe"][0] - results["1f1b"][0]) < 1e-4
        for a, b in zip(jax.tree.leaves(results["gpipe"][1]),
                        jax.tree.leaves(results["1f1b"][1])):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)

    # Interleaved virtual stages + zero-bubble (round 10): the same
    # dense-equivalence contract as the classic schedules. One fast cell
    # per new schedule — for zero-bubble the masked-execution (tp) cell,
    # which is the stricter path; the rest of the grid is slow.
    @pytest.mark.parametrize("dp,pp,tp,micro,schedule,virtual", [
        pytest.param(1, 2, 1, 4, "zerobubble", 1, marks=_slow),
        pytest.param(1, 4, 1, 4, "zerobubble", 1, marks=_slow),
        pytest.param(2, 2, 1, 2, "zerobubble", 1, marks=_slow),
        # tp > 1 forces the masked (non-cond-skip) execution path
        (1, 2, 2, 2, "zerobubble", 1),
        (1, 2, 1, 4, "interleaved", 2),
        # V=1 degenerates to plain 1F1B indices
        pytest.param(1, 4, 1, 4, "interleaved", 1, marks=_slow),
        # M == pp: minimum legal microbatch count
        pytest.param(1, 2, 1, 2, "interleaved", 2, marks=_slow),
        pytest.param(2, 2, 1, 2, "interleaved", 2, marks=_slow),
        # 4 chunks of 1 layer each on 1 stage: pure virtual pipelining
        pytest.param(1, 1, 1, 2, "interleaved", 4, marks=_slow),
    ])
    def test_new_schedules_match_dense(self, devices, dp, pp, tp, micro,
                                       schedule, virtual):
        tokens = _tokens()
        dense_p, dense_loss = self._dense_step(devices, tokens)

        model = _tiny()
        mesh = make_mesh(devices[:dp * pp * tp], dp=dp, sp=1, mp=tp, pp=pp)
        tr = PipelineLMTrainer(model, mesh, num_micro=micro,
                               optimizer=_sgd(), schedule=schedule,
                               pp_virtual=virtual)
        state = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, loss = tr.train_step(state, x, y)
        got_loss = float(np.mean(np.asarray(loss)))
        assert abs(got_loss - dense_loss) < 1e-4, (schedule, virtual)

        got = unstack_block_params(
            tr.canonical_params(jax.device_get(state.params)),
            model.num_layers)
        for a, b in zip(jax.tree.leaves(dense_p), jax.tree.leaves(got)):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=3e-4, atol=3e-5,
                err_msg=f"dp={dp} pp={pp} tp={tp} micro={micro} "
                        f"{schedule} V={virtual}")

    @pytest.mark.slow  # four compiles of one geometry; the per-schedule
    # dense equivalence above pins correctness fast
    def test_all_schedules_agree_with_dropout(self, devices):
        """Every schedule draws IDENTICAL dropout masks (keys derive from
        (microbatch, DENSE layer index), independent of the schedule and
        of the virtual-stage row permutation), so one-step results must
        agree pairwise with dropout active."""
        tokens = _tokens()
        results = {}
        for schedule, virtual in (("gpipe", 1), ("1f1b", 1),
                                  ("zerobubble", 1), ("interleaved", 2)):
            model = _tiny(dropout_rate=0.3)
            mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
            tr = PipelineLMTrainer(model, mesh, num_micro=4,
                                   optimizer=_sgd(), schedule=schedule,
                                   dropout_seed=3, pp_virtual=virtual)
            state = tr.init_state(seed=7)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            state, loss = tr.train_step(state, x, y)
            results[schedule] = (
                float(np.mean(np.asarray(loss))),
                tr.canonical_params(jax.device_get(state.params)))
        ref_loss, ref_p = results["gpipe"]
        for schedule in ("1f1b", "zerobubble", "interleaved"):
            assert abs(results[schedule][0] - ref_loss) < 1e-4, schedule
            for a, b in zip(jax.tree.leaves(ref_p),
                            jax.tree.leaves(results[schedule][1])):
                np.testing.assert_allclose(
                    np.asarray(b), np.asarray(a), rtol=3e-4, atol=3e-5,
                    err_msg=schedule)

    def test_unknown_schedule_rejected(self, devices):
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
        with pytest.raises(ValueError, match="schedule"):
            PipelineLMTrainer(_tiny(), mesh, schedule="bogus")

    def test_multi_step_loss_decreases(self, devices):
        model = _tiny()
        mesh = make_mesh(devices[:8], dp=2, sp=1, mp=1, pp=4)
        tr = PipelineLMTrainer(model, mesh)
        assert (tr.dp, tr.pp, tr.num_micro) == (2, 4, 4)
        state = tr.init_state()
        x, y = tr.put_batch(*make_lm_batch(_tokens(b=8)))
        losses = []
        for _ in range(3):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestPipelineComposition:
    """K-step scan + dispatch_depth>0 ride the pipeline rung unchanged
    (round 10): the schedule engines are pure jittable functions, so the
    multi-step scan body and the async dispatch window compose with any
    schedule exactly as they do with the dense trainer."""

    def _run(self, devices, schedule, virtual, steps, step_fn):
        model = _tiny()
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
        tr = PipelineLMTrainer(model, mesh, num_micro=4, optimizer=_sgd(),
                               schedule=schedule, pp_virtual=virtual)
        state = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(_tokens()))
        return step_fn(tr, state, x, y, steps)

    @pytest.mark.slow  # k-step scan compiles on top of the same cells;
    # per-schedule dense equivalence is pinned fast above
    # (test_new_schedules_match_dense) and the scan-of-steps machinery
    # has its own fast pins in test_engine.py.
    @pytest.mark.parametrize("schedule,virtual", [
        ("zerobubble", 1),
        ("interleaved", 2),
    ])
    def test_multi_step_scan_matches_single_steps(self, devices,
                                                  schedule, virtual):
        def singles(tr, state, x, y, k):
            losses = []
            for _ in range(k):
                state, loss = tr.train_step(state, x, y)
                losses.append(float(np.mean(np.asarray(loss))))
            return losses, jax.device_get(state.params)

        def scanned(tr, state, x, y, k):
            run = tr.build_multi_step(k)
            xs = jnp.stack([x] * k)
            ys = jnp.stack([y] * k)
            state, losses = run(state, xs, ys)
            return ([float(np.mean(np.asarray(l))) for l in losses],
                    jax.device_get(state.params))

        ref_losses, ref_p = self._run(devices, schedule, virtual, 2,
                                      singles)
        got_losses, got_p = self._run(devices, schedule, virtual, 2,
                                      scanned)
        np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-5)
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=3e-4, atol=3e-5)

    def test_dispatch_depth_composes(self, devices):
        """Driving the pipelined train_step through a DispatchPipeline
        window must not change the math — only the host-side sync
        cadence."""
        from tpu_ddp.train.pipeline import DispatchPipeline

        def sync(tr, state, x, y, k):
            losses = []
            for _ in range(k):
                state, loss = tr.train_step(state, x, y)
                losses.append(float(np.mean(np.asarray(loss))))
            return losses, jax.device_get(state.params)

        def async_(tr, state, x, y, k):
            got = {}

            def harvest(step):
                return lambda loss: got.setdefault(
                    step, float(np.mean(np.asarray(loss))))

            pipe = DispatchPipeline(depth=2)
            for step in range(k):
                state, loss = tr.train_step(state, x, y)
                pipe.submit(loss, harvest(step))
            pipe.drain()
            assert pipe.stats()["harvested"] == k
            return ([got[s] for s in range(k)],
                    jax.device_get(state.params))

        ref_losses, ref_p = self._run(devices, "zerobubble", 1, 3, sync)
        got_losses, got_p = self._run(devices, "zerobubble", 1, 3, async_)
        np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-6)
        for a, b in zip(jax.tree.leaves(ref_p), jax.tree.leaves(got_p)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-6)


class TestPipelineValidation:
    def test_indivisible_layers_raises(self, devices):
        mesh = make_mesh(devices[:3], dp=1, sp=1, mp=1, pp=3)
        with pytest.raises(ValueError, match="num_layers"):
            PipelineLMTrainer(_tiny(), mesh)

    def test_seq_indivisible_by_sp_raises(self, devices):
        mesh = make_mesh(devices[:4], dp=1, sp=2, mp=1, pp=2)
        tr = PipelineLMTrainer(_tiny(), mesh, num_micro=2)
        with pytest.raises(ValueError, match="sp"):
            tr.put_batch(np.zeros((4, 31), np.int32),
                         np.zeros((4, 31), np.int32))

    def test_batch_divisibility(self, devices):
        mesh = make_mesh(devices[:4], dp=2, sp=1, mp=1, pp=2)
        tr = PipelineLMTrainer(_tiny(), mesh, num_micro=2)
        with pytest.raises(ValueError, match="not divisible"):
            tr.put_batch(np.zeros((6, 32), np.int32),
                         np.zeros((6, 32), np.int32))

    # --- round-10 schedule constraints (mirrored by tune/space.py) ---

    def test_virtual_requires_interleaved(self, devices):
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
        for schedule in ("gpipe", "1f1b", "zerobubble"):
            with pytest.raises(ValueError, match="pp_virtual"):
                PipelineLMTrainer(_tiny(), mesh, schedule=schedule,
                                  pp_virtual=2)

    def test_interleaved_layer_divisibility(self, devices):
        # 4 layers, pp=2, V=4 -> layers % (pp*V) = 4 % 8 != 0
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
        with pytest.raises(ValueError, match="pp_virtual"):
            PipelineLMTrainer(_tiny(), mesh, schedule="interleaved",
                              pp_virtual=4)

    def test_interleaved_micro_divisibility(self, devices):
        # interleaved needs num_micro % pp == 0 (work items advance in
        # groups of pp microbatches)
        mesh = make_mesh(devices[:2], dp=1, sp=1, mp=1, pp=2)
        with pytest.raises(ValueError, match="num_micro"):
            PipelineLMTrainer(_tiny(), mesh, schedule="interleaved",
                              pp_virtual=2, num_micro=3)

    def test_virtual_requires_replicated_param_layouts(self, devices):
        # the flat dp-padded ZeRO layouts slice blocks without knowing
        # about the row permutation; V>1 refuses them
        mesh = make_mesh(devices[:4], dp=2, sp=1, mp=1, pp=2)
        with pytest.raises(ValueError, match="replicated"):
            PipelineLMTrainer(_tiny(), mesh, schedule="interleaved",
                              pp_virtual=2, num_micro=2,
                              opt_sharding="zero1")
