"""Pallas kernel correctness vs the jnp reference implementations.

Runs in interpreter mode on the forced-CPU host platform (conftest.py);
the same code path compiles on TPU.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.vgg import BN_EPS
from tpu_ddp.ops.optim import SGD
from tpu_ddp.ops.pallas import batch_norm_relu, fused_sgd_step


def _bn_relu_ref(x, scale, bias):
    """jnp reference: batch-stat BN over all-but-channel axes, then ReLU."""
    axes = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    inv = jax.lax.rsqrt(var + BN_EPS) * scale
    return jnp.maximum((x - mean) * inv + bias, 0.0)


def _tree_close(a, b, **kw):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(
        np.asarray(x), np.asarray(y), **kw), a, b)


class TestFusedSGD:
    def _toy_tree(self, key):
        k = jax.random.split(key, 6)
        return {
            "conv": {"kernel": jax.random.normal(k[0], (3, 3, 3, 64)),
                     "bias": jax.random.normal(k[1], (64,))},
            "head": {"kernel": jax.random.normal(k[2], (512, 10)),
                     "bias": jax.random.normal(k[3], (10,))},
            # Deliberately lane-unaligned sizes:
            "odd": jax.random.normal(k[4], (7, 13)),
            "scalarish": jax.random.normal(k[5], (1,)),
        }

    def test_matches_reference_sgd(self):
        params = self._toy_tree(jax.random.key(0))
        grads = self._toy_tree(jax.random.key(1))
        ref = SGD(use_pallas=False)
        pal = SGD(use_pallas=True)
        state_r = ref.init(params)
        state_p = pal.init(params)
        p_r, p_p = params, params
        for _ in range(3):  # multiple steps exercise momentum accumulation
            p_r, state_r = ref.apply(p_r, grads, state_r)
            p_p, state_p = pal.apply(p_p, grads, state_p)
        _tree_close(p_p, p_r, rtol=1e-6, atol=1e-6)
        _tree_close(state_p["momentum"], state_r["momentum"],
                    rtol=1e-6, atol=1e-6)

    def test_zero_weight_decay(self):
        params = {"w": jnp.ones((130,))}
        grads = {"w": jnp.full((130,), 2.0)}
        buf = {"w": jnp.zeros((130,))}
        new_p, new_b = fused_sgd_step(params, grads, buf, lr=0.1,
                                      momentum=0.0, weight_decay=0.0)
        np.testing.assert_allclose(np.asarray(new_p["w"]),
                                   np.full((130,), 0.8), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(new_b["w"]),
                                   np.full((130,), 2.0), rtol=1e-6)

    def test_inside_jit(self):
        opt = SGD(use_pallas=True)
        params = {"w": jnp.arange(300, dtype=jnp.float32)}
        state = opt.init(params)

        @jax.jit
        def step(p, g, s):
            return opt.apply(p, g, s)

        p2, s2 = step(params, {"w": jnp.ones((300,))}, state)
        assert p2["w"].shape == (300,)


class TestBatchNormRelu:
    @pytest.mark.parametrize("shape", [(32, 4, 4, 64), (16, 8, 8, 96),
                                       (64, 3)])
    def test_forward_matches_reference(self, shape):
        x = jax.random.normal(jax.random.key(0), shape) * 3 + 1
        c = shape[-1]
        scale = jax.random.uniform(jax.random.key(1), (c,), minval=0.5,
                                   maxval=1.5)
        bias = jax.random.normal(jax.random.key(2), (c,)) * 0.1
        got = batch_norm_relu(x, scale, bias)
        want = _bn_relu_ref(x, scale, bias)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_gradients_match_reference(self):
        shape = (8, 4, 4, 32)
        c = shape[-1]
        x = jax.random.normal(jax.random.key(0), shape) * 2
        scale = jnp.ones((c,)) * 1.3
        bias = jnp.full((c,), 0.05)

        def loss_pallas(x, s, b):
            return jnp.sum(batch_norm_relu(x, s, b) ** 2)

        def loss_ref(x, s, b):
            return jnp.sum(_bn_relu_ref(x, s, b) ** 2)

        g_p = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, scale, bias)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
        for a, b_ in zip(g_p, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)

    def test_resnet_with_pallas_bn_matches(self):
        from tpu_ddp.models import get_model
        x = jax.random.normal(jax.random.key(5), (2, 32, 32, 3))
        m_ref = get_model("ResNet50", num_classes=10, small_inputs=True,
                          compute_dtype=jnp.float32)
        m_pal = get_model("ResNet50", num_classes=10, small_inputs=True,
                          compute_dtype=jnp.float32, use_pallas_bn=True)
        params = m_ref.init(jax.random.key(0))
        np.testing.assert_allclose(
            np.asarray(m_pal.apply(params, x)),
            np.asarray(m_ref.apply(params, x)), rtol=1e-3, atol=1e-3)

    def test_vgg_with_pallas_bn_matches(self):
        from tpu_ddp.models import get_model
        x = jax.random.normal(jax.random.key(3), (4, 32, 32, 3))
        m_ref = get_model("VGG11", compute_dtype=jnp.float32)
        m_pal = get_model("VGG11", compute_dtype=jnp.float32,
                          use_pallas_bn=True)
        params = m_ref.init(jax.random.key(89395))
        np.testing.assert_allclose(
            np.asarray(m_pal.apply(params, x)),
            np.asarray(m_ref.apply(params, x)), rtol=1e-3, atol=1e-3)


class TestPallasTrainStep:
    @pytest.mark.slow  # full VGG trainer compile; kernel exactness is
    # TestFusedSGD's job — this only checks the cfg wiring end to end
    def test_trainer_with_pallas_sgd(self):
        """The fused optimizer works inside the full jitted train step."""
        from tpu_ddp.models import get_model
        from tpu_ddp.train.engine import Trainer
        from tpu_ddp.utils.config import TrainConfig

        cfg = TrainConfig(pallas_sgd=True, global_batch_size=8)
        model = get_model("VGG11", compute_dtype=jnp.float32)
        tr = Trainer(model, cfg, strategy="none")
        state = tr.init_state()
        x = np.random.default_rng(0).normal(
            size=(8, 32, 32, 3)).astype(np.float32)
        y = np.arange(8, dtype=np.int32) % 10
        xb, yb, wb = tr.put_batch(x, y)
        state2, loss = tr.train_step(state, xb, yb, wb)
        assert np.isfinite(float(loss))
