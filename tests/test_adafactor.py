"""Adafactor — factored-second-moment optimizer (tpu_ddp/ops/optim.py).

Decisive properties: (i) matrix leaves store O(n+m) state, not O(nm);
(ii) the rank-1 reconstruction is EXACT when g² is rank-1, so a factored
step equals a full-moment step there; (iii) it trains the LM family end
to end through LMTrainer; (iv) it refuses the compositions its factored
state cannot support (sharded leaves, ZeRO re-layout) instead of
silently misfactoring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.optim import Adafactor
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.train.lm import LMTrainer, make_lm_batch


class TestState:
    def test_factored_state_is_sublinear(self):
        opt = Adafactor(min_dim_size_to_factor=8)
        params = {"w": jnp.ones((64, 32)), "b": jnp.ones((64,)),
                  "tiny": jnp.ones((4, 4))}
        s = opt.init(params)
        assert s["vr"]["w"].shape == (64,)      # rows
        assert s["vc"]["w"].shape == (32,)      # cols
        assert s["v"]["w"].shape == (1,)        # full moment unused
        assert s["v"]["b"].shape == (64,)       # vectors: exact moment
        assert s["v"]["tiny"].shape == (4, 4)   # below threshold: exact
        assert s["mu"]["w"].shape == (1,)       # no momentum by default

    def test_3d_leaf_factors_last_two_dims(self):
        opt = Adafactor(min_dim_size_to_factor=8)
        s = opt.init({"w": jnp.ones((3, 16, 8))})
        assert s["vr"]["w"].shape == (3, 16)
        assert s["vc"]["w"].shape == (3, 8)

    def test_attention_shaped_leaf_factors_via_split(self):
        """(dm, 3, heads, head_dim) with head_dim below the threshold:
        the old rule fell back to a FULL second moment; the split plan
        views it as (dm, 3*heads*head_dim) and factors O(n+m)."""
        opt = Adafactor(min_dim_size_to_factor=16)
        s = opt.init({"wqkv": jnp.ones((64, 3, 4, 8))})
        assert s["vr"]["wqkv"].shape == (64,)
        assert s["vc"]["wqkv"].shape == (96,)
        assert s["v"]["wqkv"].shape == (1,)  # no O(nm) fallback

    def test_split_plan_update_matches_reshaped_2d(self):
        """The split-factored update of a 4-D leaf must equal the batch-
        factored update of the same data reshaped to the 2-D view."""
        rng = np.random.default_rng(7)
        p4 = rng.normal(size=(32, 2, 4, 8)).astype(np.float32)
        g4 = rng.normal(size=(32, 2, 4, 8)).astype(np.float32)
        opt = Adafactor(min_dim_size_to_factor=16)
        p_new4, _ = opt.apply({"w": jnp.asarray(p4)},
                              {"w": jnp.asarray(g4)},
                              opt.init({"w": jnp.asarray(p4)}))
        p2, g2 = p4.reshape(32, 64), g4.reshape(32, 64)
        p_new2, _ = opt.apply({"w": jnp.asarray(p2)},
                              {"w": jnp.asarray(g2)},
                              opt.init({"w": jnp.asarray(p2)}))
        np.testing.assert_allclose(
            np.asarray(p_new4["w"]).reshape(32, 64),
            np.asarray(p_new2["w"]), rtol=1e-5, atol=1e-8)


class TestUpdateMath:
    def test_first_step_unit_gradient(self):
        """c=1: beta2_t=0, V=g²=1 -> u=1, RMS clip no-op, relative step
        alpha = min(1e-2, 1) * max(eps2, RMS(p)=1) = 1e-2."""
        opt = Adafactor(min_dim_size_to_factor=2)
        p = {"w": jnp.ones((4, 4))}
        g = {"w": jnp.ones((4, 4))}
        new_p, state = opt.apply(p, g, opt.init(p))
        np.testing.assert_allclose(np.asarray(new_p["w"]),
                                   0.99 * np.ones((4, 4)), rtol=1e-5)
        assert int(state["count"]) == 1

    def test_factored_matches_full_on_rank1_g2(self):
        """g² rank-1 -> the factored reconstruction is exact, so the
        factored step equals the full-moment (unfactored) step."""
        rng = np.random.default_rng(0)
        a = rng.uniform(0.5, 2.0, size=(16, 1))
        b = rng.uniform(0.5, 2.0, size=(1, 12))
        g = {"w": jnp.asarray(np.sqrt(a * b), jnp.float32)}
        p = {"w": jnp.asarray(rng.normal(size=(16, 12)), jnp.float32)}
        fact = Adafactor(min_dim_size_to_factor=2)
        full = Adafactor(min_dim_size_to_factor=10_000)
        p_f, _ = fact.apply(p, g, fact.init(p))
        p_u, _ = full.apply(p, g, full.init(p))
        np.testing.assert_allclose(np.asarray(p_f["w"]),
                                   np.asarray(p_u["w"]),
                                   rtol=1e-5, atol=1e-6)

    def test_clipping_bounds_update_rms(self):
        """A wildly scaled gradient cannot move params faster than
        clip_threshold * alpha allows."""
        opt = Adafactor(min_dim_size_to_factor=10_000,
                        learning_rate=0.01, clip_threshold=1.0)
        p = {"w": jnp.zeros((8, 8))}
        g = {"w": 1e6 * jnp.asarray(
            np.random.default_rng(1).normal(size=(8, 8)), jnp.float32)}
        new_p, _ = opt.apply(p, g, opt.init(p))
        rms = float(jnp.sqrt(jnp.mean(jnp.square(new_p["w"] / 0.01))))
        assert rms <= 1.0 + 1e-5

    def test_momentum_state_allocated_when_b1(self):
        opt = Adafactor(min_dim_size_to_factor=8, b1=0.9)
        p = {"w": jnp.ones((16, 16))}
        s = opt.init(p)
        assert s["mu"]["w"].shape == (16, 16)
        new_p, s2 = opt.apply(p, {"w": jnp.ones((16, 16))}, s)
        assert float(jnp.abs(s2["mu"]["w"]).max()) > 0


class TestTrainerIntegration:
    def test_lm_trains_and_loss_drops(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        # Paper-default relative step size (learning_rate=None).
        tr = LMTrainer(model, mesh,
                       optimizer=Adafactor(min_dim_size_to_factor=8))
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(0).integers(0, 1024, size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(5):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_checkpoint_roundtrip(self, devices, tmp_path):
        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        opt = Adafactor(min_dim_size_to_factor=8, learning_rate=1e-2)
        tr = LMTrainer(model, mesh, optimizer=opt)
        state = tr.init_state(seed=3)
        tokens = np.random.default_rng(3).integers(0, 1024, size=(2, 17))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)
        resumed, _ = tr.train_step(tr.restore_checkpoint(str(tmp_path)),
                                   x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)

    def test_bare_state_specs_refuse_sharded_leaves(self):
        """The BARE optimizer still refuses sharded specs (its reduced
        state shapes have no global layout without the cell axes the
        CellAdafactor wrapper adds) — the trainers wrap automatically."""
        from jax.sharding import PartitionSpec as P
        opt = Adafactor(min_dim_size_to_factor=8)
        with pytest.raises(NotImplementedError, match="CellAdafactor"):
            opt.state_specs({"w": P(None, "mp")})

    def test_refuses_zero_relayout(self):
        opt = Adafactor(min_dim_size_to_factor=8)
        s = opt.init({"w": jnp.ones((16, 16))})
        with pytest.raises(NotImplementedError, match="FactoredZeRO1"):
            opt.map_param_like(s, lambda t: t)


def _sharded_adafactor_step(mesh, wrapper, params, per_worker_grads,
                            opt_state):
    """Run wrapper.apply inside a shard_map over dp; per_worker_grads is
    a list of dp grad trees (stacked on a leading axis for sharding)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_ddp.parallel.mesh import DATA_AXIS

    specs = wrapper.state_specs()
    stacked = jax.tree.map(lambda *gs: jnp.stack(gs), *per_worker_grads)

    def step(p, state, g):
        g = jax.tree.map(lambda x: x[0], g)  # my worker's grad tree
        return wrapper.apply(p, g, state)

    # jit, not eager: an un-jitted shard_map dispatches the wrapper's
    # hundreds of per-leaf collective ops one by one (~15 s per call on
    # this 1-core host, measured); jitted, the compile lands in the
    # persistent cache and repeat calls are milliseconds.
    mapped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), specs, P(DATA_AXIS)),
        out_specs=(P(), specs), check_vma=False))
    state_sh = jax.device_put(
        opt_state, jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P)))
    return mapped(params, state_sh, stacked)


class TestFactoredZeRO1:
    """The row-sharded ZeRO-1 wrapper must be EXACT vs the replicated
    optimizer fed the dp-mean gradient (tpu_ddp/parallel/zero.py)."""

    def _params(self):
        rng = np.random.default_rng(11)
        return {
            "w": jnp.asarray(rng.normal(size=(24, 16)), jnp.float32),
            "wqkv": jnp.asarray(rng.normal(size=(16, 3, 2, 4)),
                                jnp.float32),      # split plan
            "stack": jnp.asarray(rng.normal(size=(3, 16, 8)),
                                 jnp.float32),      # batch plan
            "b": jnp.asarray(rng.normal(size=(10,)), jnp.float32),
        }

    def _grads(self, n):
        rng = np.random.default_rng(23)
        p = self._params()
        return [jax.tree.map(
            lambda x: jnp.asarray(rng.normal(size=x.shape), jnp.float32),
            p) for _ in range(n)]

    @pytest.mark.parametrize("b1,lr", [(None, None), (0.9, 1e-2)])
    def test_matches_replicated(self, devices, b1, lr):
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.parallel.zero import FactoredZeRO1

        mesh = make_mesh(devices[:4], dp=4)
        opt = Adafactor(min_dim_size_to_factor=8, b1=b1, learning_rate=lr,
                        weight_decay=0.01)
        params = self._params()
        per_worker = self._grads(4)
        wrapper = FactoredZeRO1(opt, axis_size=4, template=params)
        p_sh, s_sh = _sharded_adafactor_step(
            mesh, wrapper, params, per_worker, wrapper.init(params))

        g_mean = jax.tree.map(lambda *gs: sum(gs) / 4.0, *per_worker)
        p_ref, s_ref = opt.apply(params, g_mean, opt.init(params))

        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_sh[k]), np.asarray(p_ref[k]),
                rtol=2e-5, atol=1e-6, err_msg=f"param {k}")
        # State matches in CANONICAL form (pad rows sliced off).
        canon = wrapper.canonicalize_opt_host(jax.device_get(s_sh))
        for part in ("vr", "vc", "v"):
            for k in params:
                np.testing.assert_allclose(
                    np.asarray(canon[part][k]),
                    np.asarray(s_ref[part][k]),
                    rtol=2e-5, atol=1e-6, err_msg=f"{part}/{k}")

    def test_two_steps_stay_exact(self, devices):
        """Factored statistics accumulate across steps; a second step
        catches any drift the first step's zero-init state hides."""
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.parallel.zero import FactoredZeRO1

        mesh = make_mesh(devices[:2], dp=2)
        opt = Adafactor(min_dim_size_to_factor=8)
        params = self._params()
        wrapper = FactoredZeRO1(opt, axis_size=2, template=params)
        state = wrapper.init(params)
        p_ref, s_ref = params, opt.init(params)
        for step_i in range(2):
            per_worker = self._grads(2)
            params, state = _sharded_adafactor_step(
                mesh, wrapper, params, per_worker, state)
            g_mean = jax.tree.map(lambda *gs: sum(gs) / 2.0, *per_worker)
            p_ref, s_ref = opt.apply(p_ref, g_mean, s_ref)
        for k in p_ref:
            np.testing.assert_allclose(
                np.asarray(params[k]), np.asarray(p_ref[k]),
                rtol=2e-5, atol=1e-6, err_msg=f"param {k} after 2 steps")

    def test_state_is_sharded_1_over_n(self, devices):
        """The memory claim: vr (and mu under b1) shard over dp."""
        from tpu_ddp.parallel.mesh import DATA_AXIS, make_mesh
        from tpu_ddp.parallel.zero import FactoredZeRO1

        del devices
        opt = Adafactor(min_dim_size_to_factor=8, b1=0.9)
        params = {"w": jnp.ones((24, 16))}
        wrapper = FactoredZeRO1(opt, axis_size=4, template=params)
        state = wrapper.init(params)
        specs = wrapper.state_specs()
        assert state["vr"]["w"].shape == (24,)
        assert tuple(specs["vr"]["w"]) == (DATA_AXIS,)
        assert state["mu"]["w"].shape == (24, 16)
        assert tuple(specs["mu"]["w"]) == (DATA_AXIS, None)
        assert tuple(specs["vc"]["w"]) == ()

    def test_canonicalize_flatten_roundtrip(self):
        from tpu_ddp.parallel.zero import FactoredZeRO1

        opt = Adafactor(min_dim_size_to_factor=8, b1=0.9)
        params = self._params()
        wrapper = FactoredZeRO1(opt, axis_size=4, template=params)
        state = jax.device_get(wrapper.init(params))
        canon = wrapper.canonicalize_opt_host(state)
        # Canonical shapes == the replicated optimizer's state shapes.
        ref = jax.device_get(opt.init(params))
        for part in ("vr", "vc", "v", "mu"):
            for k in params:
                assert np.shape(canon[part][k]) == \
                    np.shape(ref[part][k]), f"{part}/{k}"
        back = wrapper.flatten_opt(canon)
        for part in ("vr", "vc", "v", "mu"):
            for k in params:
                np.testing.assert_array_equal(
                    np.asarray(back[part][k]), np.asarray(state[part][k]),
                    err_msg=f"{part}/{k}")

    def test_lmtrainer_zero1_matches_replicated(self, devices):
        """LMTrainer(opt_sharding='zero1') with Adafactor: losses track
        the replicated run step for step."""
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        tokens = np.random.default_rng(5).integers(0, 1024, size=(4, 33))
        losses = {}
        for sharding in ("replicated", "zero1"):
            tr = LMTrainer(model, mesh,
                           optimizer=Adafactor(min_dim_size_to_factor=8),
                           opt_sharding=sharding)
            state = tr.init_state(seed=0)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            run = []
            for _ in range(3):
                state, loss = tr.train_step(state, x, y)
                run.append(float(np.mean(np.asarray(loss))))
            losses[sharding] = run
        np.testing.assert_allclose(losses["zero1"], losses["replicated"],
                                   rtol=1e-4)

    @pytest.mark.slow  # AdamW-under-zero1 parity is pinned fast and at
    # length by tests/test_zero.py; this re-checks it from the factored side
    def test_lmtrainer_zero1_adamw_matches_replicated(self, devices):
        """The elementwise branch: AdamW under opt_sharding='zero1' goes
        through the flat ZeRO1 wrapper and must match too."""
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import AdamW
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        tokens = np.random.default_rng(6).integers(0, 1024, size=(4, 33))
        losses = {}
        for sharding in ("replicated", "zero1"):
            tr = LMTrainer(model, mesh, optimizer=AdamW(),
                           opt_sharding=sharding)
            state = tr.init_state(seed=0)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            run = []
            for _ in range(3):
                state, loss = tr.train_step(state, x, y)
                run.append(float(np.mean(np.asarray(loss))))
            losses[sharding] = run
        np.testing.assert_allclose(losses["zero1"], losses["replicated"],
                                   rtol=1e-4)

    @pytest.mark.slow  # cross-layout restore; the same-layout roundtrip in
    # TestFactoredZeRO1Partitioned stays fast, cross-layout is pinned by
    # test_zero.py / test_fsdp.py
    def test_zero1_checkpoint_restores_into_replicated(self, devices,
                                                       tmp_path):
        """zero1 checkpoints hold canonical shapes: a replicated trainer
        restores them and continues identically."""
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        opt = Adafactor(min_dim_size_to_factor=8, learning_rate=1e-2)
        tokens = np.random.default_rng(9).integers(0, 1024, size=(2, 17))
        tr = LMTrainer(model, mesh, optimizer=opt, opt_sharding="zero1")
        state = tr.init_state(seed=3)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)

        repl = LMTrainer(model, mesh, optimizer=opt)
        resumed = repl.restore_checkpoint(str(tmp_path))
        resumed, _ = repl.train_step(resumed, x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)


def _cellify(tree, parts):
    """Replace each partitioned leaf with the TUPLE of its mp cells —
    the "sliced parameter tree" the per-cell ground truth runs on."""
    l_l, treedef = jax.tree.flatten(tree)
    out = []
    for x, pt in zip(l_l, parts):
        if pt is None:
            out.append(np.asarray(x))
        else:
            from tpu_ddp.parallel.zero import _part_cells
            out.append(tuple(_part_cells(np.asarray(x), pt)))
    return treedef.unflatten(out)


def _uncellify(celled_tree, parts, like):
    """Inverse of :func:`_cellify`. The celled tree's full flatten emits
    each original leaf's cells contiguously in row-major order (depth-
    first traversal preserves position order), so regroup by each
    part's cell count and reassemble."""
    from tpu_ddp.parallel.zero import _part_assemble
    flat = jax.tree.leaves(celled_tree)
    treedef = jax.tree.structure(like)
    out, i = [], 0
    for pt in parts:
        k = pt.count if pt is not None else 1
        chunk, i = flat[i:i + k], i + k
        out.append(np.asarray(chunk[0]) if pt is None
                   else _part_assemble([np.asarray(c) for c in chunk],
                                       pt))
    return treedef.unflatten(out)


class TestCellAdafactor:
    """Per-cell factoring under tensor/expert sharding (round-5): the
    sharded run must equal DENSE Adafactor run on the SLICED parameter
    tree — the T5X per-cell ground truth, which is NOT the dense run's
    factored state sliced (each cell's row/col moments are statistics
    of its own slice only)."""

    def _parts(self, model, sizes):
        from tpu_ddp.parallel.zero import _LeafMeta, _leaf_partition
        specs = model.param_specs()
        template = jax.eval_shape(lambda: model.init(jax.random.key(7)))
        from jax.sharding import PartitionSpec as P
        parts_tree = jax.tree.map(
            lambda s, t: _leaf_partition(s, _LeafMeta(t), sizes, ""),
            specs, template, is_leaf=lambda x: isinstance(x, P))
        from tpu_ddp.parallel.zero import _LeafPart
        return jax.tree.leaves(
            parts_tree,
            is_leaf=lambda x: x is None or isinstance(x, _LeafPart))

    # Both cells ride the slow tier (two tp compiles each against a
    # per-cell dense ground truth); the per-cell state LAYOUT stays
    # pinned fast by test_tp_state_layout, and the ep cell below keeps
    # a fast training pin.
    @pytest.mark.slow
    @pytest.mark.parametrize("b1", [None, 0.9])
    def test_tp_matches_per_cell_ground_truth(self, devices, b1):
        from tpu_ddp.parallel.mesh import MODEL_AXIS

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        opt = Adafactor(min_dim_size_to_factor=8, b1=b1,
                        weight_decay=1e-3)
        tokens = np.random.default_rng(5).integers(0, 1024, size=(4, 33))

        # Sharded run: dp=1 x tp=2, replicated opt -> auto CellAdafactor.
        mesh = make_mesh(devices[:2], dp=1, mp=2)
        tr = LMTrainer(model, mesh, optimizer=opt)
        state = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        for _ in range(3):
            state, _ = tr.train_step(state, x, y)
        got = jax.device_get(state.params)

        # Ground truth: dense Adafactor on the sliced tree, eagerly.
        tp_model = model.with_tensor_parallel(MODEL_AXIS, 2)
        parts = self._parts(tp_model, {MODEL_AXIS: 2})
        params = jax.device_get(model.init(jax.random.key(7)))
        inputs, targets = make_lm_batch(tokens)

        def loss(p):
            from tpu_ddp.ops.loss import softmax_cross_entropy
            logits = model.apply(p, jnp.asarray(inputs, jnp.int32))
            return jnp.mean(softmax_cross_entropy(
                logits.reshape(-1, logits.shape[-1]),
                jnp.asarray(targets, jnp.int32).reshape(-1)))

        grad_fn = jax.jit(jax.grad(loss))
        celled_p = _cellify(params, parts)
        opt_state = opt.init(celled_p)
        for _ in range(3):
            g = jax.device_get(grad_fn(params))
            celled_g = _cellify(g, parts)
            celled_p, opt_state = opt.apply(celled_p, celled_g, opt_state)
            celled_p = jax.device_get(celled_p)
            params = _uncellify(celled_p, parts, params)

        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(got)):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-5, atol=1e-6)

    def test_ep_trains_and_state_is_per_cell(self, devices):
        """MoE under ep: expert leaves' vr gains a leading ep cell axis
        and the run trains; the vr for w1 is per (ep-cell, expert,
        row)."""
        from jax.sharding import PartitionSpec as P
        from tpu_ddp.parallel.mesh import EXPERT_AXIS

        model = make_transformer(
            "TransformerLM-moe-tiny", max_seq_len=32, d_model=128,
            d_ff=256, compute_dtype=jnp.float32, moe_capacity_factor=8.0)
        mesh = make_mesh(devices[:4], dp=2, ep=2)
        tr = LMTrainer(model, mesh,
                       optimizer=Adafactor(min_dim_size_to_factor=8))
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(0).integers(0, 1024, size=(8, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(4):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]
        vr = state.opt_state["vr"]["blocks"][0]["w1"]
        # (ep_cells, E_local, dm) — leading cell axis sharded over ep.
        assert vr.shape[0] == 2
        assert vr.sharding.spec == P(EXPERT_AXIS)

    def test_pipeline_replicated_opt_trains(self, devices):
        """Adafactor under pp (previously refused at state_specs): the
        stacked per-stage cells factor independently and training
        runs."""
        from tpu_ddp.train.lm import PipelineLMTrainer

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=2, pp=2)
        tr = PipelineLMTrainer(model, mesh, num_micro=2,
                               optimizer=Adafactor(
                                   min_dim_size_to_factor=8))
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(1).integers(0, 1024, size=(8, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(4):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]


class TestFactoredZeRO1Partitioned:
    """zero1 Adafactor x tp/ep/pp (round-5): per-cell factoring with dp
    row-sharding WITHIN each cell. The decisive equivalence: it must
    match the replicated-optimizer per-cell run (CellAdafactor) on the
    same mesh — same per-cell statistics, dp-sharded storage."""

    def _run(self, devices, model, opt_sharding, n, steps=3, **mesh_kw):
        tokens = np.random.default_rng(5).integers(0, 1024, size=(8, 33))
        mesh = make_mesh(devices[:n], **mesh_kw)
        tr = LMTrainer(model, mesh,
                       optimizer=Adafactor(min_dim_size_to_factor=8),
                       opt_sharding=opt_sharding)
        state = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(steps):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        return tr, state, losses

    # test_tp_state_layout pins the partitioned-tp layout fast and
    # TestFactoredZeRO1 pins the zero1 equivalence; this full tp
    # equivalence composes the two -> slow tier.
    @pytest.mark.slow
    def test_tp_matches_replicated_opt(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        _, s_repl, l_repl = self._run(devices, model, "replicated", 4,
                                      dp=2, mp=2)
        tr, s_z, l_z = self._run(devices, model, "zero1", 4, dp=2, mp=2)
        np.testing.assert_allclose(l_z, l_repl, rtol=1e-4)
        for a, b in zip(jax.tree.leaves(jax.device_get(s_repl.params)),
                        jax.tree.leaves(jax.device_get(s_z.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-5, atol=1e-6)

    def test_tp_state_layout(self, devices):
        """vr of a tp-sharded leaf: leading mp cell axis, rows dp-
        sharded within the cell — P(mp, None..., dp); 1/(tp*dp) real
        rows per device."""
        from jax.sharding import PartitionSpec as P
        from tpu_ddp.parallel.mesh import DATA_AXIS, MODEL_AXIS

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        tr, state, _ = self._run(devices, model, "zero1", 4, steps=1,
                                 dp=2, mp=2)
        vr = state.opt_state["vr"]["blocks"][0]["w1"]
        spec = tuple(vr.sharding.spec)
        assert spec[0] == MODEL_AXIS and spec[-1] == DATA_AXIS, spec
        assert vr.addressable_shards[0].data.size == vr.size // 4

    @pytest.mark.slow  # the factored-state roundtrip is pinned fast by
    # TestTrainerIntegration::test_checkpoint_roundtrip; this adds tp
    def test_tp_checkpoint_roundtrip_same_layout(self, devices,
                                                 tmp_path):
        """Per-cell factored state is layout-coupled: the SAME dp x tp
        trainer restores and continues identically (cross-layout restore
        is documented to fail loudly)."""
        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        tokens = np.random.default_rng(9).integers(0, 1024, size=(4, 17))
        mesh = make_mesh(jax.devices()[:4], dp=2, mp=2)
        opt = Adafactor(min_dim_size_to_factor=8, learning_rate=1e-2)
        tr = LMTrainer(model, mesh, optimizer=opt, opt_sharding="zero1")
        state = tr.init_state(seed=3)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)

        tr2 = LMTrainer(model, mesh, optimizer=opt, opt_sharding="zero1")
        resumed = tr2.restore_checkpoint(str(tmp_path))
        resumed, _ = tr2.train_step(resumed, x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)

    @pytest.mark.slow  # two pp x zero1 Adafactor compiles; the
    # factored-zero1 parity itself is pinned fast by
    # test_lmtrainer_zero1_matches_replicated above.
    def test_pp_zero1_matches_replicated_opt(self, devices):
        """Pipeline x zero1 Adafactor (the last guard of the round-4
        matrix): per-cell on the stacked stage slices, matches the
        replicated-opt per-cell run."""
        from tpu_ddp.train.lm import PipelineLMTrainer

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        tokens = np.random.default_rng(5).integers(0, 1024, size=(8, 33))

        def run(opt_sharding):
            mesh = make_mesh(devices[:4], dp=2, pp=2)
            tr = PipelineLMTrainer(
                model, mesh, num_micro=2,
                optimizer=Adafactor(min_dim_size_to_factor=8),
                opt_sharding=opt_sharding)
            state = tr.init_state(seed=7)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            losses = []
            for _ in range(3):
                state, loss = tr.train_step(state, x, y)
                losses.append(float(np.mean(np.asarray(loss))))
            return state, losses

        s_repl, l_repl = run("replicated")
        s_z, l_z = run("zero1")
        np.testing.assert_allclose(l_z, l_repl, rtol=1e-4)
        for a, b in zip(jax.tree.leaves(jax.device_get(s_repl.params)),
                        jax.tree.leaves(jax.device_get(s_z.params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=2e-5, atol=1e-6)

    def test_clip_still_refused(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=2, mp=2)
        with pytest.raises(ValueError, match="clip"):
            LMTrainer(model, mesh,
                      optimizer=Adafactor(min_dim_size_to_factor=8),
                      opt_sharding="zero1", clip_grad_norm=1.0)
