"""Adafactor — factored-second-moment optimizer (tpu_ddp/ops/optim.py).

Decisive properties: (i) matrix leaves store O(n+m) state, not O(nm);
(ii) the rank-1 reconstruction is EXACT when g² is rank-1, so a factored
step equals a full-moment step there; (iii) it trains the LM family end
to end through LMTrainer; (iv) it refuses the compositions its factored
state cannot support (sharded leaves, ZeRO re-layout) instead of
silently misfactoring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.optim import Adafactor
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.train.lm import LMTrainer, make_lm_batch


class TestState:
    def test_factored_state_is_sublinear(self):
        opt = Adafactor(min_dim_size_to_factor=8)
        params = {"w": jnp.ones((64, 32)), "b": jnp.ones((64,)),
                  "tiny": jnp.ones((4, 4))}
        s = opt.init(params)
        assert s["vr"]["w"].shape == (64,)      # rows
        assert s["vc"]["w"].shape == (32,)      # cols
        assert s["v"]["w"].shape == (1,)        # full moment unused
        assert s["v"]["b"].shape == (64,)       # vectors: exact moment
        assert s["v"]["tiny"].shape == (4, 4)   # below threshold: exact
        assert s["mu"]["w"].shape == (1,)       # no momentum by default

    def test_3d_leaf_factors_last_two_dims(self):
        opt = Adafactor(min_dim_size_to_factor=8)
        s = opt.init({"w": jnp.ones((3, 16, 8))})
        assert s["vr"]["w"].shape == (3, 16)
        assert s["vc"]["w"].shape == (3, 8)

    def test_attention_shaped_leaf_factors_via_split(self):
        """(dm, 3, heads, head_dim) with head_dim below the threshold:
        the old rule fell back to a FULL second moment; the split plan
        views it as (dm, 3*heads*head_dim) and factors O(n+m)."""
        opt = Adafactor(min_dim_size_to_factor=16)
        s = opt.init({"wqkv": jnp.ones((64, 3, 4, 8))})
        assert s["vr"]["wqkv"].shape == (64,)
        assert s["vc"]["wqkv"].shape == (96,)
        assert s["v"]["wqkv"].shape == (1,)  # no O(nm) fallback

    def test_split_plan_update_matches_reshaped_2d(self):
        """The split-factored update of a 4-D leaf must equal the batch-
        factored update of the same data reshaped to the 2-D view."""
        rng = np.random.default_rng(7)
        p4 = rng.normal(size=(32, 2, 4, 8)).astype(np.float32)
        g4 = rng.normal(size=(32, 2, 4, 8)).astype(np.float32)
        opt = Adafactor(min_dim_size_to_factor=16)
        p_new4, _ = opt.apply({"w": jnp.asarray(p4)},
                              {"w": jnp.asarray(g4)},
                              opt.init({"w": jnp.asarray(p4)}))
        p2, g2 = p4.reshape(32, 64), g4.reshape(32, 64)
        p_new2, _ = opt.apply({"w": jnp.asarray(p2)},
                              {"w": jnp.asarray(g2)},
                              opt.init({"w": jnp.asarray(p2)}))
        np.testing.assert_allclose(
            np.asarray(p_new4["w"]).reshape(32, 64),
            np.asarray(p_new2["w"]), rtol=1e-5, atol=1e-8)


class TestUpdateMath:
    def test_first_step_unit_gradient(self):
        """c=1: beta2_t=0, V=g²=1 -> u=1, RMS clip no-op, relative step
        alpha = min(1e-2, 1) * max(eps2, RMS(p)=1) = 1e-2."""
        opt = Adafactor(min_dim_size_to_factor=2)
        p = {"w": jnp.ones((4, 4))}
        g = {"w": jnp.ones((4, 4))}
        new_p, state = opt.apply(p, g, opt.init(p))
        np.testing.assert_allclose(np.asarray(new_p["w"]),
                                   0.99 * np.ones((4, 4)), rtol=1e-5)
        assert int(state["count"]) == 1

    def test_factored_matches_full_on_rank1_g2(self):
        """g² rank-1 -> the factored reconstruction is exact, so the
        factored step equals the full-moment (unfactored) step."""
        rng = np.random.default_rng(0)
        a = rng.uniform(0.5, 2.0, size=(16, 1))
        b = rng.uniform(0.5, 2.0, size=(1, 12))
        g = {"w": jnp.asarray(np.sqrt(a * b), jnp.float32)}
        p = {"w": jnp.asarray(rng.normal(size=(16, 12)), jnp.float32)}
        fact = Adafactor(min_dim_size_to_factor=2)
        full = Adafactor(min_dim_size_to_factor=10_000)
        p_f, _ = fact.apply(p, g, fact.init(p))
        p_u, _ = full.apply(p, g, full.init(p))
        np.testing.assert_allclose(np.asarray(p_f["w"]),
                                   np.asarray(p_u["w"]),
                                   rtol=1e-5, atol=1e-6)

    def test_clipping_bounds_update_rms(self):
        """A wildly scaled gradient cannot move params faster than
        clip_threshold * alpha allows."""
        opt = Adafactor(min_dim_size_to_factor=10_000,
                        learning_rate=0.01, clip_threshold=1.0)
        p = {"w": jnp.zeros((8, 8))}
        g = {"w": 1e6 * jnp.asarray(
            np.random.default_rng(1).normal(size=(8, 8)), jnp.float32)}
        new_p, _ = opt.apply(p, g, opt.init(p))
        rms = float(jnp.sqrt(jnp.mean(jnp.square(new_p["w"] / 0.01))))
        assert rms <= 1.0 + 1e-5

    def test_momentum_state_allocated_when_b1(self):
        opt = Adafactor(min_dim_size_to_factor=8, b1=0.9)
        p = {"w": jnp.ones((16, 16))}
        s = opt.init(p)
        assert s["mu"]["w"].shape == (16, 16)
        new_p, s2 = opt.apply(p, {"w": jnp.ones((16, 16))}, s)
        assert float(jnp.abs(s2["mu"]["w"]).max()) > 0


class TestTrainerIntegration:
    def test_lm_trains_and_loss_drops(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        # Paper-default relative step size (learning_rate=None).
        tr = LMTrainer(model, mesh,
                       optimizer=Adafactor(min_dim_size_to_factor=8))
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(0).integers(0, 1024, size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(5):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_checkpoint_roundtrip(self, devices, tmp_path):
        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        opt = Adafactor(min_dim_size_to_factor=8, learning_rate=1e-2)
        tr = LMTrainer(model, mesh, optimizer=opt)
        state = tr.init_state(seed=3)
        tokens = np.random.default_rng(3).integers(0, 1024, size=(2, 17))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)
        resumed, _ = tr.train_step(tr.restore_checkpoint(str(tmp_path)),
                                   x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)

    def test_refuses_tensor_sharded_params(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=2, mp=2)
        with pytest.raises(NotImplementedError, match="factored"):
            LMTrainer(model, mesh,
                      optimizer=Adafactor(min_dim_size_to_factor=8))

    def test_refuses_zero_relayout(self):
        opt = Adafactor(min_dim_size_to_factor=8)
        s = opt.init({"w": jnp.ones((16, 16))})
        with pytest.raises(NotImplementedError, match="FactoredZeRO1"):
            opt.map_param_like(s, lambda t: t)


def _sharded_adafactor_step(mesh, wrapper, params, per_worker_grads,
                            opt_state):
    """Run wrapper.apply inside a shard_map over dp; per_worker_grads is
    a list of dp grad trees (stacked on a leading axis for sharding)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpu_ddp.parallel.mesh import DATA_AXIS

    specs = wrapper.state_specs()
    stacked = jax.tree.map(lambda *gs: jnp.stack(gs), *per_worker_grads)

    def step(p, state, g):
        g = jax.tree.map(lambda x: x[0], g)  # my worker's grad tree
        return wrapper.apply(p, g, state)

    # jit, not eager: an un-jitted shard_map dispatches the wrapper's
    # hundreds of per-leaf collective ops one by one (~15 s per call on
    # this 1-core host, measured); jitted, the compile lands in the
    # persistent cache and repeat calls are milliseconds.
    mapped = jax.jit(jax.shard_map(
        step, mesh=mesh,
        in_specs=(P(), specs, P(DATA_AXIS)),
        out_specs=(P(), specs), check_vma=False))
    state_sh = jax.device_put(
        opt_state, jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P)))
    return mapped(params, state_sh, stacked)


class TestFactoredZeRO1:
    """The row-sharded ZeRO-1 wrapper must be EXACT vs the replicated
    optimizer fed the dp-mean gradient (tpu_ddp/parallel/zero.py)."""

    def _params(self):
        rng = np.random.default_rng(11)
        return {
            "w": jnp.asarray(rng.normal(size=(24, 16)), jnp.float32),
            "wqkv": jnp.asarray(rng.normal(size=(16, 3, 2, 4)),
                                jnp.float32),      # split plan
            "stack": jnp.asarray(rng.normal(size=(3, 16, 8)),
                                 jnp.float32),      # batch plan
            "b": jnp.asarray(rng.normal(size=(10,)), jnp.float32),
        }

    def _grads(self, n):
        rng = np.random.default_rng(23)
        p = self._params()
        return [jax.tree.map(
            lambda x: jnp.asarray(rng.normal(size=x.shape), jnp.float32),
            p) for _ in range(n)]

    @pytest.mark.parametrize("b1,lr", [(None, None), (0.9, 1e-2)])
    def test_matches_replicated(self, devices, b1, lr):
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.parallel.zero import FactoredZeRO1

        mesh = make_mesh(devices[:4], dp=4)
        opt = Adafactor(min_dim_size_to_factor=8, b1=b1, learning_rate=lr,
                        weight_decay=0.01)
        params = self._params()
        per_worker = self._grads(4)
        wrapper = FactoredZeRO1(opt, axis_size=4, template=params)
        p_sh, s_sh = _sharded_adafactor_step(
            mesh, wrapper, params, per_worker, wrapper.init(params))

        g_mean = jax.tree.map(lambda *gs: sum(gs) / 4.0, *per_worker)
        p_ref, s_ref = opt.apply(params, g_mean, opt.init(params))

        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_sh[k]), np.asarray(p_ref[k]),
                rtol=2e-5, atol=1e-6, err_msg=f"param {k}")
        # State matches in CANONICAL form (pad rows sliced off).
        canon = wrapper.canonicalize_opt_host(jax.device_get(s_sh))
        for part in ("vr", "vc", "v"):
            for k in params:
                np.testing.assert_allclose(
                    np.asarray(canon[part][k]),
                    np.asarray(s_ref[part][k]),
                    rtol=2e-5, atol=1e-6, err_msg=f"{part}/{k}")

    def test_two_steps_stay_exact(self, devices):
        """Factored statistics accumulate across steps; a second step
        catches any drift the first step's zero-init state hides."""
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.parallel.zero import FactoredZeRO1

        mesh = make_mesh(devices[:2], dp=2)
        opt = Adafactor(min_dim_size_to_factor=8)
        params = self._params()
        wrapper = FactoredZeRO1(opt, axis_size=2, template=params)
        state = wrapper.init(params)
        p_ref, s_ref = params, opt.init(params)
        for step_i in range(2):
            per_worker = self._grads(2)
            params, state = _sharded_adafactor_step(
                mesh, wrapper, params, per_worker, state)
            g_mean = jax.tree.map(lambda *gs: sum(gs) / 2.0, *per_worker)
            p_ref, s_ref = opt.apply(p_ref, g_mean, s_ref)
        for k in p_ref:
            np.testing.assert_allclose(
                np.asarray(params[k]), np.asarray(p_ref[k]),
                rtol=2e-5, atol=1e-6, err_msg=f"param {k} after 2 steps")

    def test_state_is_sharded_1_over_n(self, devices):
        """The memory claim: vr (and mu under b1) shard over dp."""
        from tpu_ddp.parallel.mesh import DATA_AXIS, make_mesh
        from tpu_ddp.parallel.zero import FactoredZeRO1

        del devices
        opt = Adafactor(min_dim_size_to_factor=8, b1=0.9)
        params = {"w": jnp.ones((24, 16))}
        wrapper = FactoredZeRO1(opt, axis_size=4, template=params)
        state = wrapper.init(params)
        specs = wrapper.state_specs()
        assert state["vr"]["w"].shape == (24,)
        assert tuple(specs["vr"]["w"]) == (DATA_AXIS,)
        assert state["mu"]["w"].shape == (24, 16)
        assert tuple(specs["mu"]["w"]) == (DATA_AXIS, None)
        assert tuple(specs["vc"]["w"]) == ()

    def test_canonicalize_flatten_roundtrip(self):
        from tpu_ddp.parallel.zero import FactoredZeRO1

        opt = Adafactor(min_dim_size_to_factor=8, b1=0.9)
        params = self._params()
        wrapper = FactoredZeRO1(opt, axis_size=4, template=params)
        state = jax.device_get(wrapper.init(params))
        canon = wrapper.canonicalize_opt_host(state)
        # Canonical shapes == the replicated optimizer's state shapes.
        ref = jax.device_get(opt.init(params))
        for part in ("vr", "vc", "v", "mu"):
            for k in params:
                assert np.shape(canon[part][k]) == \
                    np.shape(ref[part][k]), f"{part}/{k}"
        back = wrapper.flatten_opt(canon)
        for part in ("vr", "vc", "v", "mu"):
            for k in params:
                np.testing.assert_array_equal(
                    np.asarray(back[part][k]), np.asarray(state[part][k]),
                    err_msg=f"{part}/{k}")

    def test_lmtrainer_zero1_matches_replicated(self, devices):
        """LMTrainer(opt_sharding='zero1') with Adafactor: losses track
        the replicated run step for step."""
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        tokens = np.random.default_rng(5).integers(0, 1024, size=(4, 33))
        losses = {}
        for sharding in ("replicated", "zero1"):
            tr = LMTrainer(model, mesh,
                           optimizer=Adafactor(min_dim_size_to_factor=8),
                           opt_sharding=sharding)
            state = tr.init_state(seed=0)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            run = []
            for _ in range(3):
                state, loss = tr.train_step(state, x, y)
                run.append(float(np.mean(np.asarray(loss))))
            losses[sharding] = run
        np.testing.assert_allclose(losses["zero1"], losses["replicated"],
                                   rtol=1e-4)

    def test_lmtrainer_zero1_adamw_matches_replicated(self, devices):
        """The elementwise branch: AdamW under opt_sharding='zero1' goes
        through the flat ZeRO1 wrapper and must match too."""
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.ops.optim import AdamW
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        tokens = np.random.default_rng(6).integers(0, 1024, size=(4, 33))
        losses = {}
        for sharding in ("replicated", "zero1"):
            tr = LMTrainer(model, mesh, optimizer=AdamW(),
                           opt_sharding=sharding)
            state = tr.init_state(seed=0)
            x, y = tr.put_batch(*make_lm_batch(tokens))
            run = []
            for _ in range(3):
                state, loss = tr.train_step(state, x, y)
                run.append(float(np.mean(np.asarray(loss))))
            losses[sharding] = run
        np.testing.assert_allclose(losses["zero1"], losses["replicated"],
                                   rtol=1e-4)

    def test_zero1_checkpoint_restores_into_replicated(self, devices,
                                                       tmp_path):
        """zero1 checkpoints hold canonical shapes: a replicated trainer
        restores them and continues identically."""
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.parallel.mesh import make_mesh
        from tpu_ddp.train.lm import LMTrainer, make_lm_batch

        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        opt = Adafactor(min_dim_size_to_factor=8, learning_rate=1e-2)
        tokens = np.random.default_rng(9).integers(0, 1024, size=(2, 17))
        tr = LMTrainer(model, mesh, optimizer=opt, opt_sharding="zero1")
        state = tr.init_state(seed=3)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)

        repl = LMTrainer(model, mesh, optimizer=opt)
        resumed = repl.restore_checkpoint(str(tmp_path))
        resumed, _ = repl.train_step(resumed, x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-7)
