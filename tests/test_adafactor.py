"""Adafactor — factored-second-moment optimizer (tpu_ddp/ops/optim.py).

Decisive properties: (i) matrix leaves store O(n+m) state, not O(nm);
(ii) the rank-1 reconstruction is EXACT when g² is rank-1, so a factored
step equals a full-moment step there; (iii) it trains the LM family end
to end through LMTrainer; (iv) it refuses the compositions its factored
state cannot support (sharded leaves, ZeRO re-layout) instead of
silently misfactoring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.ops.optim import Adafactor
from tpu_ddp.parallel.mesh import make_mesh
from tpu_ddp.train.lm import LMTrainer, make_lm_batch


class TestState:
    def test_factored_state_is_sublinear(self):
        opt = Adafactor(min_dim_size_to_factor=8)
        params = {"w": jnp.ones((64, 32)), "b": jnp.ones((64,)),
                  "tiny": jnp.ones((4, 4))}
        s = opt.init(params)
        assert s["vr"]["w"].shape == (64,)      # rows
        assert s["vc"]["w"].shape == (32,)      # cols
        assert s["v"]["w"].shape == (1,)        # full moment unused
        assert s["v"]["b"].shape == (64,)       # vectors: exact moment
        assert s["v"]["tiny"].shape == (4, 4)   # below threshold: exact
        assert s["mu"]["w"].shape == (1,)       # no momentum by default

    def test_3d_leaf_factors_last_two_dims(self):
        opt = Adafactor(min_dim_size_to_factor=8)
        s = opt.init({"w": jnp.ones((3, 16, 8))})
        assert s["vr"]["w"].shape == (3, 16)
        assert s["vc"]["w"].shape == (3, 8)


class TestUpdateMath:
    def test_first_step_unit_gradient(self):
        """c=1: beta2_t=0, V=g²=1 -> u=1, RMS clip no-op, relative step
        alpha = min(1e-2, 1) * max(eps2, RMS(p)=1) = 1e-2."""
        opt = Adafactor(min_dim_size_to_factor=2)
        p = {"w": jnp.ones((4, 4))}
        g = {"w": jnp.ones((4, 4))}
        new_p, state = opt.apply(p, g, opt.init(p))
        np.testing.assert_allclose(np.asarray(new_p["w"]),
                                   0.99 * np.ones((4, 4)), rtol=1e-5)
        assert int(state["count"]) == 1

    def test_factored_matches_full_on_rank1_g2(self):
        """g² rank-1 -> the factored reconstruction is exact, so the
        factored step equals the full-moment (unfactored) step."""
        rng = np.random.default_rng(0)
        a = rng.uniform(0.5, 2.0, size=(16, 1))
        b = rng.uniform(0.5, 2.0, size=(1, 12))
        g = {"w": jnp.asarray(np.sqrt(a * b), jnp.float32)}
        p = {"w": jnp.asarray(rng.normal(size=(16, 12)), jnp.float32)}
        fact = Adafactor(min_dim_size_to_factor=2)
        full = Adafactor(min_dim_size_to_factor=10_000)
        p_f, _ = fact.apply(p, g, fact.init(p))
        p_u, _ = full.apply(p, g, full.init(p))
        np.testing.assert_allclose(np.asarray(p_f["w"]),
                                   np.asarray(p_u["w"]),
                                   rtol=1e-5, atol=1e-6)

    def test_clipping_bounds_update_rms(self):
        """A wildly scaled gradient cannot move params faster than
        clip_threshold * alpha allows."""
        opt = Adafactor(min_dim_size_to_factor=10_000,
                        learning_rate=0.01, clip_threshold=1.0)
        p = {"w": jnp.zeros((8, 8))}
        g = {"w": 1e6 * jnp.asarray(
            np.random.default_rng(1).normal(size=(8, 8)), jnp.float32)}
        new_p, _ = opt.apply(p, g, opt.init(p))
        rms = float(jnp.sqrt(jnp.mean(jnp.square(new_p["w"] / 0.01))))
        assert rms <= 1.0 + 1e-5

    def test_momentum_state_allocated_when_b1(self):
        opt = Adafactor(min_dim_size_to_factor=8, b1=0.9)
        p = {"w": jnp.ones((16, 16))}
        s = opt.init(p)
        assert s["mu"]["w"].shape == (16, 16)
        new_p, s2 = opt.apply(p, {"w": jnp.ones((16, 16))}, s)
        assert float(jnp.abs(s2["mu"]["w"]).max()) > 0


class TestTrainerIntegration:
    def test_lm_trains_and_loss_drops(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        # Paper-default relative step size (learning_rate=None).
        tr = LMTrainer(model, mesh,
                       optimizer=Adafactor(min_dim_size_to_factor=8))
        state = tr.init_state(seed=0)
        tokens = np.random.default_rng(0).integers(0, 1024, size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(5):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_checkpoint_roundtrip(self, devices, tmp_path):
        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:2], dp=2)
        opt = Adafactor(min_dim_size_to_factor=8, learning_rate=1e-2)
        tr = LMTrainer(model, mesh, optimizer=opt)
        state = tr.init_state(seed=3)
        tokens = np.random.default_rng(3).integers(0, 1024, size=(2, 17))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, _ = tr.train_step(state, x, y)
        tr.save_checkpoint(str(tmp_path), state)
        cont, _ = tr.train_step(state, x, y)
        resumed, _ = tr.train_step(tr.restore_checkpoint(str(tmp_path)),
                                   x, y)
        for a, b in zip(jax.tree.leaves(jax.device_get(cont.params)),
                        jax.tree.leaves(jax.device_get(resumed.params))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)

    def test_refuses_tensor_sharded_params(self, devices):
        model = make_transformer("TransformerLM-tiny", max_seq_len=16,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=2, mp=2)
        with pytest.raises(NotImplementedError, match="factored"):
            LMTrainer(model, mesh,
                      optimizer=Adafactor(min_dim_size_to_factor=8))

    def test_refuses_zero_relayout(self):
        opt = Adafactor(min_dim_size_to_factor=8)
        s = opt.init({"w": jnp.ones((16, 16))})
        with pytest.raises(NotImplementedError, match="re-laid-out"):
            opt.map_param_like(s, lambda t: t)
