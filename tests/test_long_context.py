"""Long-context serving (tpu_ddp/serve/long_context.py, DESIGN.md §27):
the tiered KV pool's residency state machine, the tier-accounting
identity fuzz (satellite of §27), the promote-before-trim rollback fix,
tiered-engine exactness against the single-pool oracle, and
context-parallel chunked prefill parity on the forced 8-device host
platform.

Exactness strategy: the bf16 hot tier with the bf16 cold codec is
LOSSLESS (parallel/compress.py stores a plain downcast with unit
scales), so a tiers=3 engine under HBM pressure must emit the EXACT
token stream of a tiers=1 bf16 engine — demote/spill/promote traffic
changes where bytes live, never what they are. The int8 codec is
semantic (rounded re-reads) and is exercised for liveness + accounting
only.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.parallel.mesh import make_mesh, replicated_sharding
from tpu_ddp.serve import (
    PagedKVPool,
    Request,
    Scheduler,
    ServeEngine,
    make_long_prompt_workload,
)

# The shared fast-tier cache geometry (tests/test_serve.py): tiered
# engines reuse the same logical pool so the scheduler math is
# identical; only hbm_blocks/cold_blocks vary the residency pressure.
GEOM = dict(num_slots=4, block_size=8, prefill_chunk=8)


@pytest.fixture(scope="module")
def model():
    return make_transformer("TransformerLM-tiny", max_seq_len=64,
                            compute_dtype=jnp.float32)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def _prompt(L, seed=0):
    return np.random.default_rng(seed).integers(0, 1024, size=L,
                                                dtype=np.int64)


def _stream(model, params, cases, **kw):
    """Greedy streams for ``cases = [(prompt_len, max_new), ...]``
    through one engine configuration."""
    cfg = dict(GEOM)
    cfg.update(kw)
    eng = ServeEngine(model, params, **cfg)
    reqs = [eng.submit(_prompt(L, seed=100 + i), n)
            for i, (L, n) in enumerate(cases)]
    eng.run()
    assert all(r.done and not r.cancelled for r in reqs)
    assert eng.pool.free_count == eng.pool.total_usable
    assert eng.sched.accounting_ok()
    return [np.asarray(r.tokens) for r in reqs]


# ---------------------------------------------------------------------------
# Tiered pool mechanics
# ---------------------------------------------------------------------------

class TestTieredPool:
    def test_tiers1_is_identity(self, model):
        # The default pool is the round-12 layout bit-for-bit: logical
        # id == hot slot, no cold buffers, trivial tier accounting.
        pool = PagedKVPool(model, 9, 8)
        b = pool.alloc()
        assert pool.hot_slot(b) == b
        assert pool.cold_k is None
        assert pool.tier_of(b) == "hot"
        assert pool.tier_accounting_ok()
        hot, cold = pool.slot_tables([b], 4)
        assert hot[0] == b and not cold.any()

    def test_geometry_validation(self, model):
        with pytest.raises(ValueError, match="tiers"):
            PagedKVPool(model, 9, 8, tiers=4)
        with pytest.raises(ValueError, match="cold_dtype"):
            PagedKVPool(model, 9, 8, tiers=2, cold_dtype="fp4")
        with pytest.raises(ValueError, match="hbm_blocks"):
            PagedKVPool(model, 9, 8, tiers=2, hbm_blocks=1)
        with pytest.raises(ValueError, match="cold_blocks"):
            PagedKVPool(model, 9, 8, tiers=2, cold_blocks=1)

    def test_lifecycle_fresh_to_spill_and_back(self, model):
        # FREE -> FRESH -> HOT -> COLD -> SPILL -> COLD -> HOT, driven
        # purely by residency pressure (hot_usable=2, cold usable=2,
        # tiers=3 so the overflow lands on the host).
        pool = PagedKVPool(model, 9, 8, tiers=3, hbm_blocks=3,
                           cold_blocks=3)
        blocks = [pool.alloc() for _ in range(6)]
        assert all(pool.tier_of(b) == "fresh" for b in blocks)
        for b in blocks:
            pool.ensure_hot([b])
        counts = pool.tier_counts()
        assert counts["hot"] == 2 and counts["cold"] == 2
        assert counts["spill"] == 2
        assert pool.tier_accounting_ok()
        spilled = [b for b in blocks if pool.tier_of(b) == "spill"]
        # slot_tables refuses spilled pages: residency is an explicit
        # precondition of every step program, never an implicit fetch.
        with pytest.raises(RuntimeError, match="spill"):
            pool.slot_tables([spilled[0]], 4)
        pool.ensure_device(spilled)
        assert all(pool.tier_of(b) == "cold" for b in spilled)
        pool.ensure_hot([spilled[0]])
        assert pool.tier_of(spilled[0]) == "hot"
        assert pool.tier_accounting_ok()
        pool.free(blocks)
        assert pool.tier_counts()["hot"] == 0
        assert pool.free_count == pool.total_usable
        assert pool.tier_accounting_ok()

    def test_overcommitted_ensure_hot_is_loud(self, model):
        pool = PagedKVPool(model, 9, 8, tiers=3, hbm_blocks=3,
                           cold_blocks=3)
        blocks = [pool.alloc() for _ in range(3)]
        with pytest.raises(RuntimeError, match="hot"):
            pool.ensure_hot(blocks)  # 3 targets > hot_usable == 2

    def test_tiers2_has_no_spill_tier(self, model):
        # tiers=2 keeps cold pages in HBM only: once hot+cold is full,
        # further residency demands must fail loudly, not silently
        # drop pages.
        pool = PagedKVPool(model, 9, 8, tiers=2, hbm_blocks=3,
                           cold_blocks=3)
        blocks = [pool.alloc() for _ in range(5)]
        for b in blocks[:4]:
            pool.ensure_hot([b])
        with pytest.raises(RuntimeError, match="cold"):
            pool.ensure_hot([blocks[4]])

    def test_bf16_spill_roundtrip_is_lossless(self, model):
        # The parity-bearing tier: bf16 hot + bf16 cold stores a plain
        # downcast (unit scales), so HOT -> COLD -> SPILL -> HOT
        # returns the exact bytes.
        pool = PagedKVPool(model, 9, 8, "bf16", tiers=3, hbm_blocks=3,
                           cold_blocks=3, cold_dtype="bf16")
        b = pool.alloc()
        pool.ensure_hot([b])
        rng = np.random.default_rng(0)
        page = jnp.asarray(rng.standard_normal(
            pool.k[:, 0].shape), jnp.bfloat16)
        s = pool.hot_slot(b)
        pool.k = pool.k.at[:, s].set(page)
        pool.v = pool.v.at[:, s].set(-page)
        others = [pool.alloc() for _ in range(4)]
        for o in others:          # evict b all the way to the host
            pool.ensure_hot([o])
        assert pool.tier_of(b) == "spill"
        pool.ensure_device([b])
        pool.ensure_hot([b])
        kb, vb = pool.page_arrays([b])
        np.testing.assert_array_equal(np.asarray(kb[:, 0], np.float32),
                                      np.asarray(page, np.float32))
        np.testing.assert_array_equal(np.asarray(vb[:, 0], np.float32),
                                      np.asarray(-page, np.float32))

    def test_int8_roundtrip_is_close(self, model):
        pool = PagedKVPool(model, 9, 8, tiers=3, hbm_blocks=3,
                           cold_blocks=3, cold_dtype="int8")
        b = pool.alloc()
        pool.ensure_hot([b])
        rng = np.random.default_rng(1)
        page = jnp.asarray(rng.standard_normal(pool.k[:, 0].shape),
                           jnp.float32)
        pool.k = pool.k.at[:, pool.hot_slot(b)].set(page)
        others = [pool.alloc() for _ in range(4)]
        for o in others:
            pool.ensure_hot([o])
        assert pool.tier_of(b) == "spill"
        pool.ensure_hot([b])
        kb, _ = pool.page_arrays([b])
        # Per-token-row scale = max|x|/127: worst-case rounding error
        # is scale/2, and |x| <= ~5 sigma here.
        np.testing.assert_allclose(np.asarray(kb[:, 0]),
                                   np.asarray(page), atol=0.05)

    def test_cow_of_spilled_source(self, model):
        pool = PagedKVPool(model, 17, 8, "bf16", tiers=3, hbm_blocks=4,
                           cold_blocks=4, cold_dtype="bf16")
        b = pool.alloc()
        pool.ensure_hot([b])
        page = jnp.ones(pool.k[:, 0].shape, jnp.bfloat16)
        pool.k = pool.k.at[:, pool.hot_slot(b)].set(page)
        for _ in range(6):        # push b off the device entirely
            pool.ensure_hot([pool.alloc()])
        assert pool.tier_of(b) == "spill"
        new = pool.cow(b)
        assert pool.tier_of(new) == "hot" and pool.tier_of(b) == "hot"
        kb, _ = pool.page_arrays([new])
        np.testing.assert_array_equal(np.asarray(kb[:, 0], np.float32),
                                      np.ones(kb[:, 0].shape, np.float32))

    def test_scrub_reaches_every_tier(self, model):
        pool = PagedKVPool(model, 9, 8, tiers=3, hbm_blocks=3,
                           cold_blocks=3)
        blocks = [pool.alloc() for _ in range(6)]
        for b in blocks:
            pool.ensure_hot([b])
            s = pool.hot_slot(b)
            pool.k = pool.k.at[:, s].set(jnp.nan)
            pool.v = pool.v.at[:, s].set(jnp.nan)
        # Poison now lives in hot slots, cold pages and host spill.
        pool.scrub(blocks)
        for b in blocks:          # one at a time: device holds 4 pages
            pool.ensure_device([b])
            pool.ensure_hot([b])
            kb, vb = pool.page_arrays([b])
            assert not np.isnan(np.asarray(kb, np.float32)).any()
            assert not np.isnan(np.asarray(vb, np.float32)).any()


# ---------------------------------------------------------------------------
# Satellite: the tier-accounting identity, fuzzed
# ---------------------------------------------------------------------------

class TestTierAccountingFuzz:
    @pytest.mark.parametrize("tiers,seed", [(2, 0), (3, 1), (3, 2)])
    def test_identity_holds_under_random_ops(self, model, tiers, seed):
        """``hot_free + hot_resident == hot usable`` (and the cold
        analog) through a random storm of alloc / free / incref / cow /
        scrub / spill / promote, with the full refcount identity
        checked via ``refcount_ok`` after EVERY op. tiers=2 runs the
        same storm with no spill tier (residency demands that overflow
        hot+cold raise instead)."""
        cold = 40 if tiers == 2 else 6
        pool = PagedKVPool(model, 33, 8, tiers=tiers, hbm_blocks=5,
                           cold_blocks=cold)
        rng = np.random.default_rng(seed)
        holders: list[list[int]] = []

        def live():
            return sorted({b for h in holders for b in h})

        for _ in range(250):
            op = rng.integers(0, 7)
            if op == 0 and pool.free_count:
                holders.append([pool.alloc()])
            elif op == 1 and holders:
                dead = holders.pop(rng.integers(len(holders)))
                pool.free(dead)
            elif op == 2 and live():
                b = int(rng.choice(live()))
                pool.incref([b])
                holders.append([b])
            elif op == 3 and live() and pool.free_count:
                b = int(rng.choice(live()))
                try:
                    holders.append([pool.cow(b)])
                except RuntimeError:
                    pass          # tiers=2 device full: loud, not wrong
            elif op == 4 and live():
                n = int(rng.integers(1, pool.hot_usable + 1))
                pick = list(rng.choice(live(), size=min(n, len(live())),
                                       replace=False))
                try:
                    pool.ensure_hot([int(b) for b in pick])
                except RuntimeError:
                    pass
            elif op == 5 and live():
                pick = list(rng.choice(live(),
                                       size=min(3, len(live())),
                                       replace=False))
                pool.ensure_device([int(b) for b in pick])
            elif op == 6 and live():
                pool.scrub([int(rng.choice(live()))])
            assert pool.refcount_ok(holders), \
                f"accounting identity broken after op {op}"
        for h in holders:
            pool.free(h)
        assert pool.free_count == pool.total_usable
        assert pool.tier_counts()["spill"] == 0
        assert pool.refcount_ok([])


# ---------------------------------------------------------------------------
# Satellite: promote-before-trim (the speculative rollback fix)
# ---------------------------------------------------------------------------

class TestPromoteBeforeTrim:
    def test_trim_promotes_the_kept_frontier(self, model):
        """A deep rollback lands the write frontier in a block that
        residency pressure demoted while the speculative window raced
        ahead. ``trim_blocks`` must promote that block BEFORE freeing
        the tail — the next decode step scatters into its hot slot."""
        pool = PagedKVPool(model, 33, 8, tiers=3, hbm_blocks=4,
                           cold_blocks=33)
        sched = Scheduler(pool, num_slots=1)
        sched.enqueue(Request(rid=0, prompt=np.zeros(8, np.int32),
                              max_new_tokens=40))
        idx = sched.admit()[0]
        s = sched.slots[idx]
        sched.ensure_blocks(idx, 32)          # speculative over-growth
        assert len(s.blocks) > pool.hot_usable
        fi = s.length // pool.block_size
        frontier = s.blocks[fi]
        # Pressure from the speculative tail pushes the frontier off
        # the device: hot_usable == 3, four distinct blocks demand
        # residency, and the frontier is the LRU-coldest.
        pool.ensure_hot([frontier])
        for b in s.blocks[:fi] + s.blocks[fi + 1:]:
            pool.ensure_hot([b])
        assert pool.tier_of(frontier) != "hot"
        sched.trim_blocks(idx)
        assert pool.tier_of(frontier) == "hot"
        assert len(s.blocks) == s.length // pool.block_size + 1
        assert pool.refcount_ok([s.blocks])

    # The scheduler-level promote-before-trim test above pins the fix
    # directly; this end-to-end spec-chain composition adds only the
    # engine plumbing on top -> slow tier.
    @pytest.mark.slow
    def test_spec_chain_under_tiny_hbm_matches_oracle(self, model,
                                                      params):
        """Engine-level regression: spec_k > 0 with an HBM budget far
        below the working set. The chain draft re-dispatches the
        bitwise-exact decode program, so the stream must equal the
        tiers=1 bf16 engine's plain greedy stream even while every
        step's rollback trims through demoted blocks."""
        cases = [(9, 10), (4, 12)]
        want = _stream(model, params, cases, cache_dtype="bf16")
        got = _stream(model, params, cases, cache_dtype="bf16",
                      kv_tiers=3, kv_cold_dtype="bf16", hbm_blocks=9,
                      cold_blocks=33, num_slots=2, spec_k=3,
                      spec_draft="chain")
        for w, g in zip(want, got):
            np.testing.assert_array_equal(g, w)


# ---------------------------------------------------------------------------
# Tiered engine exactness + liveness
# ---------------------------------------------------------------------------

class TestTieredEngine:
    def test_bf16_tiered_stream_matches_single_pool(self, model,
                                                    params):
        """The §27 exactness bar: tiers=3 under real pressure (hot
        tier holds 5 of up to 32 live pages; spill exercised) emits
        the EXACT stream of the tiers=1 bf16 oracle across a mixed
        continuous batch."""
        cases = [(3, 6), (11, 6), (20, 4), (9, 12)]
        want = _stream(model, params, cases, cache_dtype="bf16")
        got = _stream(model, params, cases, cache_dtype="bf16",
                      kv_tiers=3, kv_cold_dtype="bf16", hbm_blocks=6,
                      cold_blocks=33)
        for i, (w, g) in enumerate(zip(want, got)):
            np.testing.assert_array_equal(
                g, w, err_msg=f"request {i} diverged under tiering")

    # The chain-spec tiered test above covers speculation x tiering;
    # the fused family only adds the all-hot slot-translation case.
    @pytest.mark.slow
    def test_fused_spec_all_hot_translation(self, model, params):
        # Fused drafts run the round-17 program against HOT SLOT ids:
        # exact only when whole tables fit hot. Streams must match the
        # tiers=1 engine running the same fused draft.
        cases = [(5, 8), (9, 6)]
        want = _stream(model, params, cases, cache_dtype="bf16",
                       num_slots=2, spec_k=2, spec_draft="self-1")
        got = _stream(model, params, cases, cache_dtype="bf16",
                      kv_tiers=3, kv_cold_dtype="bf16", hbm_blocks=33,
                      cold_blocks=33, num_slots=2, spec_k=2,
                      spec_draft="self-1")
        for w, g in zip(want, got):
            np.testing.assert_array_equal(g, w)

    def test_int8_cold_tier_liveness(self, model, params):
        # The semantic codec: full-length generations through the same
        # programs, accounting clean; no token-level claim.
        eng = ServeEngine(model, params, **GEOM, kv_tiers=3,
                          kv_cold_dtype="int8", hbm_blocks=6,
                          cold_blocks=33)
        reqs = [eng.submit(_prompt(L, seed=40 + i), n)
                for i, (L, n) in enumerate([(10, 6), (17, 5)])]
        eng.run()
        assert all(r.done and len(r.tokens) == n
                   for r, (_, n) in zip(reqs, [(10, 6), (17, 5)]))
        assert eng.pool.free_count == eng.pool.total_usable
        assert eng.pool.tier_accounting_ok()

    def test_long_prompt_workload_exceeds_hot_capacity(self, model,
                                                       params):
        # The tentpole claim in miniature: a prompt needing 6 blocks
        # served with 3 hot pages — total context bounded by the
        # logical pool, hot context by hbm_blocks.
        spec = make_long_prompt_workload(1, model.vocab_size, seed=7,
                                         prompt_len=44, max_new=(4, 5))[0]
        eng = ServeEngine(model, params, num_slots=1, block_size=8,
                          prefill_chunk=8, kv_tiers=3,
                          kv_cold_dtype="int8", hbm_blocks=4,
                          cold_blocks=9)
        req = eng.submit(spec.prompt, spec.max_new_tokens)
        eng.run()
        assert req.done and len(req.tokens) == spec.max_new_tokens
        assert eng.pool.tier_accounting_ok()


# ---------------------------------------------------------------------------
# Context-parallel chunked prefill
# ---------------------------------------------------------------------------

class TestCPPrefill:
    @pytest.mark.parametrize("mode", ["ring", "ulysses"])
    def test_stream_matches_single_rank(self, model, params, mode):
        """Sharding each prefill chunk's query rows over sp ranks must
        not change a single emitted token. 29-token prompt: three full
        chunks plus a ragged 5-token tail (partial final chunk, sample
        position inside the chunk)."""
        sp = 4
        mesh = make_mesh(jax.devices()[:sp], dp=1, sp=sp)
        rp = jax.device_put(params, replicated_sharding(mesh))
        cases = [(29, 6), (8, 5)]
        want = _stream(model, params, cases)
        got = _stream(model, rp, cases, cp_prefill=mode, mesh=mesh)
        for i, (w, g) in enumerate(zip(want, got)):
            np.testing.assert_array_equal(
                g, w, err_msg=f"request {i} diverged under cp={mode}")

    def test_rejected_combinations(self, model, params):
        sp = 2
        mesh = make_mesh(jax.devices()[:sp], dp=1, sp=sp)
        rp = jax.device_put(params, replicated_sharding(mesh))
        with pytest.raises(ValueError, match="single-tier"):
            ServeEngine(model, rp, **GEOM, cp_prefill="ring",
                        mesh=mesh, kv_tiers=2)
        with pytest.raises(ValueError, match="sp"):
            ServeEngine(model, params, **GEOM, cp_prefill="ring")
        with pytest.raises(ValueError, match="divide"):
            ServeEngine(model, rp, num_slots=4, block_size=8,
                        prefill_chunk=9, cp_prefill="ring", mesh=mesh)
        with pytest.raises(ValueError, match="cp_prefill"):
            ServeEngine(model, params, **GEOM, cp_prefill="dp")


# ---------------------------------------------------------------------------
# Knob surfaces
# ---------------------------------------------------------------------------

class TestKnobs:
    def test_env_defaults_flow_into_engine(self, model, params,
                                           monkeypatch):
        monkeypatch.setenv("TPU_DDP_KV_TIERS", "3")
        monkeypatch.setenv("TPU_DDP_KV_COLD_DTYPE", "bf16")
        eng = ServeEngine(model, params, **GEOM)
        assert eng.kv_tiers == 3
        assert eng.kv_cold_dtype == "bf16"
        assert eng.pool.tiers == 3

    @pytest.mark.parametrize("env,junk", [
        ("TPU_DDP_KV_TIERS", "0"),
        ("TPU_DDP_KV_TIERS", "many"),
        ("TPU_DDP_KV_COLD_DTYPE", "fp8"),
        ("TPU_DDP_CP_PREFILL", "dp"),
    ])
    def test_junk_env_rejected(self, env, junk, monkeypatch):
        from tpu_ddp.utils.config import TrainConfig
        monkeypatch.setenv(env, junk)
        with pytest.raises(ValueError, match=env):
            TrainConfig()

    def test_long_prompt_workload_shape(self):
        w = make_long_prompt_workload(5, 1024, seed=3, prompt_len=256,
                                      max_new=(4, 9))
        assert len(w) == 5
        assert all(len(s.prompt) == 256 for s in w)
        assert all(4 <= s.max_new_tokens < 9 for s in w)
        again = make_long_prompt_workload(5, 1024, seed=3,
                                          prompt_len=256, max_new=(4, 9))
        for a, b in zip(w, again):
            np.testing.assert_array_equal(a.prompt, b.prompt)
