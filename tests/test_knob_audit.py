"""The knob audit must pass on the live tree AND catch seeded drift.

A consistency checker that never fails is indistinguishable from one
that checks nothing — every drift class the audit claims to detect is
seeded here with a deliberately-broken registry entry and must produce
a finding that names the problem.
"""

import dataclasses

from scripts.knob_audit import NONPERF_ENV, audit
from tpu_ddp.tune.space import KNOBS, Knob, knob_by_field


def test_live_tree_is_clean():
    # The CI gate: any drift between TrainConfig, the env block, the
    # launch flags, and the registry fails the suite with the audit's
    # own message naming the surface that moved.
    assert audit() == []


def test_catches_missing_field():
    drifted = KNOBS + (Knob("ghost", "no_such_field",
                            "TPU_DDP_DISPATCH_DEPTH", values=(1, 2)),)
    findings = audit(drifted)
    assert any("no_such_field" in f and "does not exist" in f
               for f in findings)


def test_catches_unparsed_env_var():
    # The env var exists in no __post_init__ branch: setting it must
    # leave the field at its default, which the behavioral check flags.
    drifted = KNOBS + (Knob("drift", "dispatch_depth",
                            "TPU_DDP_NO_SUCH_VAR", values=(0, 1, 2, 4)),)
    findings = audit(drifted)
    assert any("TPU_DDP_NO_SUCH_VAR" in f and "not parsed" in f
               for f in findings)


def test_catches_env_wired_to_wrong_field():
    # TPU_DDP_PREFETCH is parsed — but into device_prefetch, not
    # steps_per_dispatch. The probe value lands in the wrong field.
    drifted = KNOBS + (Knob("crossed", "steps_per_dispatch",
                            "TPU_DDP_PREFETCH", values=(1, 4)),)
    findings = audit(drifted)
    assert any("crossed" in f for f in findings)


def test_catches_default_outside_candidates():
    bad = tuple(dataclasses.replace(k, values=(7, 9))
                if k.name == "dispatch_depth" else k for k in KNOBS)
    findings = audit(bad)
    assert any("keep the default" in f for f in findings)


def test_catches_unknown_launch_flag():
    drifted = KNOBS + (Knob("flagless", "dispatch_depth",
                            "TPU_DDP_DISPATCH_DEPTH", values=(0, 2),
                            flag="--no-such-flag"),)
    findings = audit(drifted)
    assert any("--no-such-flag" in f for f in findings)


def test_reverse_check_catches_unregistered_perf_env():
    # Drop the grad_compress entry: config.py still parses
    # TPU_DDP_GRAD_COMPRESS, so the reverse sweep must flag it as a
    # knob living outside the search space.
    pruned = tuple(k for k in KNOBS if k.name != "grad_compress")
    findings = audit(pruned)
    assert any("TPU_DDP_GRAD_COMPRESS" in f and "no registry entry" in f
               for f in findings)


def test_memory_policy_knobs_registered():
    # The two memory-policy knobs (tpu_ddp/memory/) carry the full
    # 4-surface contract; act_dtype changes numerics so it must be
    # semantic (excluded from the default search like compute_dtype),
    # remat must not be (it re-executes the same ops).
    remat = knob_by_field("remat")
    act = knob_by_field("act_dtype")
    assert remat is not None and act is not None
    assert remat.env == "TPU_DDP_REMAT" and remat.flag == "--remat"
    assert act.env == "TPU_DDP_ACT_DTYPE" and act.flag == "--act-dtype"
    assert act.semantic and not remat.semantic
    assert set(remat.values) == {"none", "blocks", "conv_stages", "dots"}
    assert set(act.values) == {"compute", "bf16", "f32"}


def test_moe_knobs_registered():
    # The three MoE knobs (tpu_ddp/parallel/moe.py) carry the full
    # 4-surface contract. All are semantic — each changes WHAT the
    # model computes (a different architecture / routing distribution),
    # so the default step_time search never wanders into them — and all
    # stay under objective="step_time" so the goodput sweeps' exact
    # field sets below are untouched.
    from tpu_ddp.tune.space import Workload, violations

    e = knob_by_field("moe_experts")
    k = knob_by_field("moe_top_k")
    c = knob_by_field("moe_capacity")
    assert e is not None and k is not None and c is not None
    assert e.env == "TPU_DDP_MOE_EXPERTS" and e.flag == "--moe-experts"
    assert k.env == "TPU_DDP_MOE_TOP_K" and k.flag == "--moe-top-k"
    assert c.env == "TPU_DDP_MOE_CAPACITY" and c.flag == "--moe-capacity"
    for knob in (e, k, c):
        assert knob.semantic and knob.objective == "step_time", knob.name
    # Candidate sets include the dense defaults (the audit's
    # keep-the-default rule) and the shipped presets' settings.
    assert 0 in e.values and 1 in k.values and 1.25 in c.values
    # Engine-mirrored violations: an ep mesh needs a MoE model whose
    # expert count it divides; top_k beyond E is a topk_route reject;
    # the routing knobs are inert duplicates of the dense default
    # without experts.
    ep2 = Workload(platform="cpu", ep=2)
    assert violations({"moe_experts": 0}, ep2)
    assert violations({"moe_experts": 5}, ep2)
    assert violations({"moe_experts": 6}, ep2) == []
    assert violations({"moe_experts": 4, "moe_top_k": 8},
                      Workload(platform="cpu"))
    assert violations({"moe_top_k": 2}, Workload(platform="cpu"))
    assert violations({"moe_capacity": 2.0}, Workload(platform="cpu"))
    assert violations({"moe_experts": 4, "moe_top_k": 2,
                       "moe_capacity": 2.0},
                      Workload(platform="cpu")) == []


def test_diloco_knobs_registered():
    # The four DiLoCo knobs (tpu_ddp/train/outer.py, DESIGN.md §29)
    # carry the full 4-surface contract. All are semantic — H local
    # steps between syncs is a different training algorithm, not a
    # schedule — and all stay under objective="step_time" so the
    # goodput sweeps' exact field sets are untouched.
    from tpu_ddp.tune.space import Workload, violations

    h = knob_by_field("diloco_h")
    lr = knob_by_field("outer_lr")
    mu = knob_by_field("outer_momentum")
    wire = knob_by_field("outer_wire")
    assert h is not None and lr is not None
    assert mu is not None and wire is not None
    assert h.env == "TPU_DDP_DILOCO_H" and h.flag == "--diloco-h"
    assert lr.env == "TPU_DDP_DILOCO_OUTER_LR"
    assert lr.flag == "--diloco-outer-lr"
    assert mu.env == "TPU_DDP_DILOCO_OUTER_MOMENTUM"
    assert mu.flag == "--diloco-outer-momentum"
    assert wire.env == "TPU_DDP_DILOCO_OUTER_WIRE"
    assert wire.flag == "--diloco-outer-wire"
    for knob in (h, lr, mu, wire):
        assert knob.semantic and knob.objective == "step_time", knob.name
    # Candidate sets include the off defaults (keep-the-default rule)
    # and the publish wire vocabulary verbatim — the outer wire IS the
    # publish codec, so the sets must not drift apart.
    assert 0 in h.values and 0.7 in lr.values and 0.9 in mu.values
    assert set(wire.values) == {"none", "bf16", "int8", "sparse"}
    # Engine-mirrored violations: the outer knobs are inert duplicates
    # of the plain-sync default without diloco_h, and DiLoCo groups
    # assume the canonical params_to_host layout — pp inside a group
    # is rejected.
    cpu = Workload(platform="cpu")
    assert violations({"outer_lr": 1.0}, cpu)
    assert violations({"outer_momentum": 0.0}, cpu)
    assert violations({"outer_wire": "int8"}, cpu)
    assert violations({"diloco_h": 8, "outer_wire": "int8"}, cpu) == []
    assert violations({"diloco_h": 8}, Workload(platform="cpu", pp=2))


def test_serve_knobs_registered_under_goodput_objective():
    # The serving knobs (tpu_ddp/serve/) carry the same 4-surface
    # contract minus the launch flag (serving is not a launch.py
    # concern), and live under objective="goodput" so the training
    # autotuner's step_time search never wanders into them — and the
    # serve sweep's goodput search gets exactly them.
    from tpu_ddp.tune.space import Workload, searchable_knobs
    from tpu_ddp.utils.config import TrainConfig

    fields = {"serve_slots", "serve_block_size", "serve_prefill_chunk",
              "serve_cache_dtype", "fleet_roles", "prefix_cache",
              "router_policy", "kv_wire",
              # Fleet-resilience knobs (DESIGN.md §23): health and
              # migration in the Router, shedding in the engine.
              "fleet_health", "fleet_probe_backoff_ms",
              "fleet_step_deadline_ms", "fleet_retry_budget",
              "serve_queue_limit", "serve_shed_ms",
              # Weight-streaming knobs (DESIGN.md §24): publish cadence
              # and wire on the trainer, staleness gate across both.
              "publish_every", "publish_wire", "max_staleness_steps",
              # Autoscaling knobs (DESIGN.md §25): replica lifecycle in
              # the Autoscaler, SLO classes in the scheduler's WFQ.
              "fleet_autoscale", "scale_cooldown_ms", "tenant_classes",
              # Speculative decoding + quantized decode (DESIGN.md
              # §26): window width and draft family in the engine,
              # int8 weights at engine construction.
              "spec_k", "spec_draft", "decode_quant",
              # Long-context serving knobs (DESIGN.md §27): tier count
              # and cold codec on the KV pool, context-parallel prefill
              # on the engine's prefill path.
              "kv_tiers", "kv_cold_dtype", "cp_prefill"}
    for f in fields:
        k = knob_by_field(f)
        assert k is not None and k.objective == "goodput", f
    assert knob_by_field("serve_block_size").env == "TPU_DDP_SERVE_BLOCK"
    assert knob_by_field("kv_wire").env == "TPU_DDP_KV_WIRE"
    assert (knob_by_field("fleet_probe_backoff_ms").env
            == "TPU_DDP_FLEET_HEALTH_BACKOFF_MS")
    assert (knob_by_field("max_staleness_steps").env
            == "TPU_DDP_PUBLISH_MAX_STALENESS")
    # Cache dtype and the lossy KV wire change numerics -> semantic,
    # like act_dtype; the pure-scheduling knobs must not be.
    assert knob_by_field("serve_cache_dtype").semantic
    assert knob_by_field("kv_wire").semantic
    assert knob_by_field("publish_wire").semantic
    assert not knob_by_field("publish_every").semantic
    assert not knob_by_field("max_staleness_steps").semantic
    assert not knob_by_field("serve_slots").semantic
    assert not knob_by_field("fleet_roles").semantic
    # Resilience knobs never change what a healthy run computes —
    # migration replay is bitwise (tests/test_fleet_resilience.py) —
    # so none of them are semantic.
    for f in ("fleet_health", "fleet_retry_budget", "serve_queue_limit",
              "serve_shed_ms"):
        assert not knob_by_field(f).semantic, f
    # Autoscaling never changes what any one request computes — drain
    # migration is bitwise and WFQ only reorders admission — so the
    # whole control plane is pure scheduling.
    for f in ("fleet_autoscale", "scale_cooldown_ms", "tenant_classes"):
        assert not knob_by_field(f).semantic, f
    # int8 decode rounds the served logits -> semantic like
    # publish_wire; speculation never changes the emitted stream (the
    # chain family is bitwise, the fused families emit only target
    # samples), so spec_k/spec_draft are pure scheduling.
    assert knob_by_field("decode_quant").semantic
    assert not knob_by_field("spec_k").semantic
    assert not knob_by_field("spec_draft").semantic
    assert knob_by_field("spec_k").env == "TPU_DDP_SPEC_K"
    # The int8 cold codec rounds re-read pages -> semantic like
    # kv_wire; the tier count and cp prefill only move/split exact
    # bytes (bitwise parity in tests/test_long_context.py), so both
    # are pure scheduling.
    assert knob_by_field("kv_cold_dtype").semantic
    assert not knob_by_field("kv_tiers").semantic
    assert not knob_by_field("cp_prefill").semantic
    cfg, ctx = TrainConfig(), Workload(platform="cpu")
    good = {k.field for k, _ in
            searchable_knobs(cfg, ctx, objective="goodput",
                             include_semantic=True)}
    # At the default config the coupled fleet knobs collapse to single
    # candidates (kv_wire needs a disagg edge, prefix-affinity needs a
    # cache, the publish wire and gate need a publish cadence, the
    # scale cooldown needs a live autoscaler, a non-chain draft needs
    # spec_k > 0 — tune/space.py violations) and drop out of the
    # space; spec_k and decode_quant are live on a single engine.
    # (kv_cold_dtype likewise collapses: it is inert until kv_tiers
    # lifts off 1, while kv_tiers and cp_prefill stay live.)
    assert good == fields - {"router_policy", "kv_wire",
                             "publish_wire", "max_staleness_steps",
                             "scale_cooldown_ms", "spec_draft",
                             "kv_cold_dtype"}
    step = {k.field for k, _ in searchable_knobs(cfg, ctx)}
    assert not (step & fields)
    # With the edge, the cache, a publish cadence, and the autoscaler
    # on, the whole fleet space opens up — EXCEPT speculation, which
    # the disagg decode tier's fused adopt+decode program excludes
    # (spec_k collapses to {0}, which in turn keeps spec_draft inert).
    fleet_cfg = TrainConfig(fleet_roles="disagg", prefix_cache=True,
                            publish_every=1, fleet_autoscale=True)
    good = {k.field for k, _ in
            searchable_knobs(fleet_cfg, ctx, objective="goodput",
                             include_semantic=True)}
    assert good == fields - {"spec_k", "spec_draft", "kv_cold_dtype"}
    # On a single engine with speculation on, the draft family opens.
    spec_cfg = TrainConfig(spec_k=4)
    good = {k.field for k, _ in
            searchable_knobs(spec_cfg, ctx, objective="goodput",
                             include_semantic=True)}
    assert "spec_draft" in good and "decode_quant" in good


def test_reverse_check_catches_unregistered_remat_env():
    # Drop the remat entry: config.py still parses TPU_DDP_REMAT, so
    # the reverse sweep must flag the knob living outside the space.
    pruned = tuple(k for k in KNOBS if k.name != "remat")
    findings = audit(pruned)
    assert any("TPU_DDP_REMAT" in f and "no registry entry" in f
               for f in findings)


def test_catches_junk_accepting_string_env():
    # Seed check (6)'s drift class: a config whose env surface swallows
    # validation errors lets junk land in string fields — the audit
    # must flag every such knob. Seeded by wrapping __post_init__ so
    # the ValueError the validators raise is suppressed (the field
    # keeps the junk the parse branch already wrote).
    from tpu_ddp.utils.config import TrainConfig
    orig = TrainConfig.__post_init__

    def sloppy(self):
        try:
            orig(self)
        except ValueError:
            pass

    TrainConfig.__post_init__ = sloppy
    try:
        findings = audit()
        assert any("knob-audit-junk" in f and "must validate" in f
                   for f in findings)
        assert any("TPU_DDP_REMAT" in f for f in findings)
    finally:
        TrainConfig.__post_init__ = orig


def test_nonperf_allowlist_is_exact():
    # Every allowlisted var must still be absent from the registry —
    # an entry appearing for one means the allowlist line should go.
    registered = {k.env for k in KNOBS}
    assert not (NONPERF_ENV & registered)
    assert knob_by_field("dispatch_depth") is not None
