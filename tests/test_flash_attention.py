"""Flash attention Pallas kernel: exact vs the jnp reference — values and
gradients, causal and not, lane-aligned and padded shapes — plus the
model-level use_flash path.

Runs in Pallas interpreter mode on the CPU test platform; the same code
compiles on TPU (tpu_ddp/ops/pallas/__init__.py:interpret_mode).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.ops.pallas import flash_attention
from tpu_ddp.parallel.ring_attention import full_attention


def _qkv(key, b=1, L=128, h=2, d=128):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, L, h, d), jnp.float32)
                 for k in ks)


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_aligned_matches_reference(self, causal):
        q, k, v = _qkv(jax.random.key(0))
        got = flash_attention(q, k, v, causal)
        want = full_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("L,d", [(100, 64), (130, 32), (48, 16)])
    def test_padded_shapes_match(self, L, d):
        """Sequence/head dims needing padding to the 128 block."""
        q, k, v = _qkv(jax.random.key(1), L=L, d=d)
        got = flash_attention(q, k, v, True)
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-5, atol=3e-5)

    def test_multi_block_sequence(self):
        """L spanning several 128-blocks exercises the online-softmax
        state across kv sweep steps."""
        q, k, v = _qkv(jax.random.key(2), L=384, d=32)
        got = flash_attention(q, k, v, True)
        want = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=3e-5, atol=3e-5)

    def test_bfloat16(self):
        q, k, v = (x.astype(jnp.bfloat16)
                   for x in _qkv(jax.random.key(3), L=64, d=64))
        got = flash_attention(q, k, v, True)
        want = full_attention(q, k, v, causal=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=2e-2, atol=2e-2)


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("L,d", [(128, 128), (100, 32)])
    def test_grads_match_reference(self, causal, L, d):
        q, k, v = _qkv(jax.random.key(4), L=L, d=d)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gr, "qkv"):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4,
                err_msg=f"d{name} causal={causal} L={L} d={d}")

    def test_mixed_dtype_differentiable(self):
        """Cotangent dtypes must match each primal's own dtype
        (regression: dk/dv once inherited q's dtype)."""
        q, k, v = _qkv(jax.random.key(6), L=64, d=32)
        q = q.astype(jnp.bfloat16)
        k = k.astype(jnp.bfloat16)  # v stays float32

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, True)
                           .astype(jnp.float32) ** 2)

        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert dq.dtype == jnp.bfloat16
        assert dk.dtype == jnp.bfloat16
        assert dv.dtype == jnp.float32

    def test_multi_block_grads(self):
        q, k, v = _qkv(jax.random.key(5), L=256, d=32)
        gf = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, True) ** 2), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda *a: jnp.sum(
            full_attention(*a, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=3e-4)


class TestModelIntegration:
    def test_use_flash_matches_dense_model(self):
        from tpu_ddp.models.transformer import make_transformer
        tokens = jnp.asarray(np.random.default_rng(0).integers(
            0, 1024, size=(2, 32)))
        base = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                compute_dtype=jnp.float32)
        flash = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32,
                                 use_flash=True)
        params = base.init(jax.random.key(0))
        want = base.apply(params, tokens)
        got = flash.apply(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)


class TestGroupedKV:
    """Native grouped-query support in the kernel: grouped K/V in,
    values and gradients exactly matching the materialized-expansion
    path (whose dk/dv are the per-group sums)."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_grouped_matches_expanded(self, causal):
        rng = np.random.default_rng(17)
        B, L, H, KV, D = 2, 128, 8, 2, 64
        q = jnp.asarray(rng.normal(size=(B, L, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, L, KV, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, L, KV, D)), jnp.float32)

        def loss_grouped(q, k, v):
            return jnp.sum(jnp.square(flash_attention(q, k, v, causal)))

        def loss_expanded(q, k, v):
            ke = jnp.repeat(k, H // KV, axis=2)
            ve = jnp.repeat(v, H // KV, axis=2)
            return jnp.sum(jnp.square(flash_attention(q, ke, ve, causal)))

        og = np.asarray(flash_attention(q, k, v, causal))
        oe = np.asarray(flash_attention(
            q, jnp.repeat(k, H // KV, axis=2),
            jnp.repeat(v, H // KV, axis=2), causal))
        np.testing.assert_allclose(og, oe, rtol=1e-5, atol=1e-5)

        gg = jax.grad(loss_grouped, argnums=(0, 1, 2))(q, k, v)
        ge = jax.grad(loss_expanded, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gg, ge, ("dq", "dk", "dv")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4,
                                       err_msg=name)

    def test_indivisible_heads_rejected(self):
        q = jnp.zeros((1, 16, 6, 32), jnp.float32)
        k = jnp.zeros((1, 16, 4, 32), jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            flash_attention(q, k, k)
