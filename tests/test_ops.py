"""Ops tests: cross-entropy and SGD vs the torch semantics the reference
uses (CrossEntropyLoss, part1/main.py:119; SGD(0.1, 0.9, 1e-4),
part1/main.py:124-125)."""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from tpu_ddp.ops import SGD, cross_entropy_loss, top1_correct


def test_cross_entropy_matches_torch():
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(16, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=16).astype(np.int64)
    ours = float(cross_entropy_loss(jnp.asarray(logits),
                                    jnp.asarray(labels.astype(np.int32))))
    theirs = float(torch.nn.CrossEntropyLoss()(
        torch.tensor(logits), torch.tensor(labels)))
    assert abs(ours - theirs) < 1e-5


def test_sgd_matches_torch_three_steps():
    rng = np.random.default_rng(1)
    w0 = rng.normal(size=(7, 5)).astype(np.float32)

    # torch side
    wt = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.SGD([wt], lr=0.1, momentum=0.9, weight_decay=1e-4)
    # ours
    sgd = SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4)
    params = {"w": jnp.asarray(w0)}
    state = sgd.init(params)

    for step in range(3):
        g = rng.normal(size=w0.shape).astype(np.float32)
        opt.zero_grad()
        wt.grad = torch.tensor(g.copy())
        opt.step()
        params, state = sgd.apply(params, {"w": jnp.asarray(g)}, state)
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   wt.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_top1_correct():
    logits = jnp.asarray([[1.0, 2.0], [5.0, 0.0], [0.0, 1.0]])
    labels = jnp.asarray([1, 0, 0])
    assert int(top1_correct(logits, labels)) == 2
