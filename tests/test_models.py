"""Model-zoo tests: VGG family parity with the reference architecture
(reference part1/model.py:1-50)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_ddp.models import make_vgg, resnet50
from tpu_ddp.models.vgg import VGG_CFG, batch_norm


def torch_vgg_param_count(name: str) -> int:
    """Parameter count of the reference torch model, built independently."""
    import torch.nn as nn

    cfg = VGG_CFG[name]
    layers, c_in = [], 3
    for w in cfg:
        if w == "M":
            layers.append(nn.MaxPool2d(2, 2))
        else:
            layers.append(nn.Conv2d(c_in, w, 3, 1, 1, bias=True))
            layers.append(nn.BatchNorm2d(w, track_running_stats=False))
            layers.append(nn.ReLU(inplace=True))
            c_in = w
    model = nn.Sequential(*layers, nn.Flatten(), nn.Linear(512, 10))
    return sum(p.numel() for p in model.parameters())


@pytest.mark.parametrize("name", list(VGG_CFG))
def test_param_count_matches_torch_reference(name):
    model = make_vgg(name)
    assert model.num_params() == torch_vgg_param_count(name)


def test_vgg11_forward_shape_and_dtype():
    model = make_vgg("VGG11")
    params = model.init(jax.random.key(0))
    x = jnp.zeros((4, 32, 32, 3), jnp.float32)
    logits = model.apply(params, x)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32


def test_vgg11_batch_independence_of_argmax_path():
    # Same input twice in a batch -> identical logits rows (BN uses batch
    # stats, so rows interact through stats, but identical rows stay equal).
    model = make_vgg("VGG11", compute_dtype=jnp.float32)
    params = model.init(jax.random.key(1))
    x1 = jax.random.normal(jax.random.key(2), (2, 32, 32, 3))
    x = jnp.concatenate([x1, x1], axis=0)
    logits = model.apply(params, x)
    np.testing.assert_allclose(logits[:2], logits[2:], rtol=1e-5, atol=1e-5)


def test_batch_norm_uses_current_batch_stats():
    # track_running_stats=False semantics (reference part1/model.py:24):
    # normalized output has ~zero mean / unit var per channel.
    x = jax.random.normal(jax.random.key(0), (8, 4, 4, 3)) * 5 + 3
    y = batch_norm(x, jnp.ones(3), jnp.zeros(3))
    np.testing.assert_allclose(np.mean(y, axis=(0, 1, 2)), 0.0, atol=1e-4)
    np.testing.assert_allclose(np.var(np.asarray(y), axis=(0, 1, 2)), 1.0,
                               atol=1e-3)


def test_vgg_init_deterministic():
    model = make_vgg("VGG11")
    p1 = model.init(jax.random.key(89395))
    p2 = model.init(jax.random.key(89395))
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow  # ResNet-50 fwd compile: minutes-scale on 1 core
def test_resnet50_small_inputs_forward():
    model = resnet50(num_classes=10, small_inputs=True,
                     compute_dtype=jnp.float32)
    params = model.init(jax.random.key(0))
    logits = model.apply(params, jnp.zeros((2, 32, 32, 3)))
    assert logits.shape == (2, 10)


def test_resnet50_param_count_close_to_canonical():
    # Canonical torchvision ResNet-50 (ImageNet) has 25,557,032 params;
    # ours differs only by BN running-stat buffers (absent here) and
    # stem/head details. Assert the same order of magnitude and exact conv
    # structure via a tight band.
    model = resnet50(num_classes=1000)
    n = model.num_params()
    assert 25_000_000 < n < 26_000_000, n
