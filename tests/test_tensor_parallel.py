"""Tensor parallelism: the tp-sharded model computes EXACTLY the same
function — values, gradients, and one full optimizer step — as the dense
single-device model, alone and composed with dp and sp.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_ddp.models.transformer import make_transformer
from tpu_ddp.parallel.mesh import (DATA_AXIS, MODEL_AXIS, SEQ_AXIS,
                                   make_mesh)
from tpu_ddp.train.lm import LMTrainer, make_lm_batch


def _tiny(**kw):
    cfg = dict(max_seq_len=32, compute_dtype=jnp.float32)
    cfg.update(kw)
    return make_transformer("TransformerLM-tiny", **cfg)


def _tp_apply(model, mesh, tp):
    sharded = model.with_tensor_parallel(MODEL_AXIS, tp)
    specs = sharded.param_specs()
    fn = jax.jit(jax.shard_map(
        sharded.apply, mesh=mesh,
        in_specs=(specs, P()), out_specs=P(), check_vma=False))
    return sharded, specs, fn


class TestTPForward:
    @pytest.mark.parametrize("tp", [2, 4])
    def test_matches_dense(self, devices, tp):
        model = _tiny()
        params = model.init(jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, 1024)
        want = model.apply(params, tokens)

        mesh = make_mesh(devices[:tp], dp=1, sp=1, mp=tp)
        _, _, fn = _tp_apply(model, mesh, tp)
        got = fn(params, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-4)

    def test_param_specs_match_tree(self):
        model = _tiny().with_tensor_parallel(MODEL_AXIS, 2)
        params = model.init(jax.random.key(0))
        specs = model.param_specs()
        assert jax.tree.structure(params) == jax.tree.structure(
            specs, is_leaf=lambda x: isinstance(x, P))

    def test_indivisible_heads_raises(self):
        model = _tiny()  # tiny has 4 heads
        with pytest.raises(ValueError, match="num_heads"):
            model.with_tensor_parallel(MODEL_AXIS, 3)


class TestTPGradients:
    def test_replicated_grads_identical_across_shards(self, devices):
        """Gradients of replicated leaves (embed, LN) must come out full
        and identical on every mp shard — the tp_input psum-backward
        invariant."""
        tp = 4
        model = _tiny().with_tensor_parallel(MODEL_AXIS, tp)
        mesh = make_mesh(devices[:tp], dp=1, sp=1, mp=tp)
        specs = model.param_specs()
        params = model.init(jax.random.key(2))
        tokens = jax.random.randint(jax.random.key(3), (2, 16), 0, 1024)

        def loss(p, t):
            return jnp.mean(model.apply(p, t) ** 2)

        # PER-SHARD grads, no sync: out_specs says replicated leaves are
        # replicated; fetching per-device shards must agree.
        grad_fn = jax.jit(jax.shard_map(
            jax.grad(loss), mesh=mesh, in_specs=(specs, P()),
            out_specs=specs, check_vma=False))
        grads = grad_fn(params, tokens)

        dense = _tiny()
        dense_params = dense.init(jax.random.key(2))
        dense_grads = jax.grad(
            lambda p, t: jnp.mean(dense.apply(p, t) ** 2))(
                dense_params, tokens)
        np.testing.assert_allclose(
            np.asarray(grads["embed"]), np.asarray(dense_grads["embed"]),
            rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads["blocks"][0]["ln1"]["scale"]),
            np.asarray(dense_grads["blocks"][0]["ln1"]["scale"]),
            rtol=2e-4, atol=1e-5)
        # Sharded leaves reassemble to the dense gradient.
        np.testing.assert_allclose(
            np.asarray(grads["blocks"][0]["w1"]),
            np.asarray(dense_grads["blocks"][0]["w1"]),
            rtol=2e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(grads["blocks"][1]["wqkv"]),
            np.asarray(dense_grads["blocks"][1]["wqkv"]),
            rtol=2e-4, atol=1e-5)


class TestLMTrainerTP:
    def _one_step_params(self, devices, dp, sp, tp, tokens):
        model = _tiny()
        mesh = make_mesh(devices[:dp * sp * tp], dp=dp, sp=sp, mp=tp)
        tr = LMTrainer(model, mesh)
        state = tr.init_state(seed=7)
        x, y = tr.put_batch(*make_lm_batch(tokens))
        state, loss = tr.train_step(state, x, y)
        mean_loss = float(np.mean(np.asarray(loss)))
        return jax.device_get(state.params), mean_loss

    def test_step_matches_dp_only(self, devices):
        """One full AdamW step under (dp=2, tp=2) and (dp=1, sp=2, tp=2)
        equals the pure-dp step — same updated params, same loss."""
        rng = np.random.default_rng(5)
        tokens = rng.integers(0, 1024, size=(4, 33))
        ref_p, ref_loss = self._one_step_params(devices, 4, 1, 1, tokens)
        for dp, sp, tp in [(2, 1, 2), (1, 2, 2), (2, 2, 2)]:
            got_p, got_loss = self._one_step_params(
                devices, dp, sp, tp, tokens)
            assert abs(got_loss - ref_loss) < 1e-4, (dp, sp, tp)
            flat_ref = jax.tree.leaves(ref_p)
            flat_got = jax.tree.leaves(got_p)
            for a, b in zip(flat_ref, flat_got):
                np.testing.assert_allclose(
                    np.asarray(b), np.asarray(a), rtol=3e-4, atol=3e-5,
                    err_msg=f"dp={dp} sp={sp} tp={tp}")

    def test_loss_decreases_under_tp(self, devices):
        model = _tiny()
        mesh = make_mesh(devices[:8], dp=2, sp=2, mp=2)
        tr = LMTrainer(model, mesh)
        assert (tr.dp, tr.sp, tr.tp) == (2, 2, 2)
        state = tr.init_state()
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 1024, size=(4, 33))
        x, y = tr.put_batch(*make_lm_batch(tokens))
        losses = []
        for _ in range(3):
            state, loss = tr.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
