"""Live TrainState redistribution (tpu_ddp/parallel/redistribute.py).

The elastic-membership contract, pinned leaf by leaf:

- the PartitionSpec JSON codec round-trips every shape of spec tree the
  strategies produce (prefix specs, per-leaf trees, tuple axes);
- every strategy rung's ``sharding_plan()`` survives serialize ->
  deserialize -> ``==`` (the plan IS the layout contract, so a lossy
  codec would silently re-shard state wrong after a membership change);
- re-resolving a plan against a different world moves ONLY the data
  axis, and refuses worlds the model axes don't divide;
- a state redistributed across a dp change is BITWISE the state that a
  fresh shard of the same canonical bytes produces — f32 params, opt
  state, step, and (same-dp) the int8 error-feedback residual;
- a checkpoint written at one dp restores at another dp with an
  identical sha256 over its canonical host bytes, for the flat-layout
  strategies where dp actually changes the device bytes.
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_ddp.parallel.mesh import DATA_AXIS, SEQ_AXIS, make_mesh
from tpu_ddp.parallel.redistribute import (ShardingPlan,
                                           broadcast_shardings,
                                           decode_spec_tree,
                                           encode_spec_tree,
                                           redistribute_state)
from tpu_ddp.train.engine import Trainer
from tpu_ddp.utils.config import TrainConfig


@dataclasses.dataclass(frozen=True)
class TinyNoBN:
    """Per-example-decoupled conv model (same rationale as
    test_sync.TinyNoBN: no batch statistics, so distributed forwards
    match the single-device pass exactly)."""

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        return {
            "conv": 0.3 * jax.random.normal(k1, (3, 3, 3, 8)),
            "bias": jnp.zeros((8,)),
            "head": 0.3 * jax.random.normal(k2, (2 * 2 * 8, 10)),
            "head_b": 0.01 * jax.random.normal(k3, (10,)),
        }

    def apply(self, params, x):
        y = lax.conv_general_dilated(
            x, params["conv"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = jnp.maximum(y + params["bias"], 0)
        y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 2, 2, 1),
                              (1, 2, 2, 1), "VALID")
        return y.reshape(y.shape[0], -1) @ params["head"] + params["head_b"]


def _batch(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4, 4, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


def _trainer(devices, strategy, dp=4, **cfg):
    mesh = make_mesh(devices[:dp]) if strategy != "none" else None
    return Trainer(TinyNoBN(), TrainConfig(**cfg), strategy=strategy,
                   mesh=mesh)


def _advance(tr, state, steps=2):
    for s in range(steps):
        state, _ = tr.train_step(state, *tr.put_batch(*_batch(seed=s)))
    return state


def _assert_host_trees_bitwise(a, b):
    al, ad = jax.tree.flatten(a)
    bl, bd = jax.tree.flatten(b)
    assert ad == bd
    for x, y in zip(al, bl):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _sha256(tree) -> str:
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(tree):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Spec-tree codec


class TestSpecCodec:
    def test_round_trip_nested(self):
        tree = {
            "prefix": P(DATA_AXIS),
            "replicated": P(),
            "tuple_axes": P((DATA_AXIS, "ep"), SEQ_AXIS),
            "with_none": P(None, DATA_AXIS),
            "stages": (P("pp"), [P(), P(DATA_AXIS)]),
            "scalar": 3,
            "none": None,
        }
        assert decode_spec_tree(encode_spec_tree(tree)) == tree

    def test_tuples_survive_as_tuples(self):
        # JSON has no tuples; the codec must not flatten them to lists
        # (tree structures would stop matching the live spec trees).
        got = decode_spec_tree(encode_spec_tree((P(), P(DATA_AXIS))))
        assert isinstance(got, tuple)
        spec = decode_spec_tree(encode_spec_tree(P((DATA_AXIS, "ep"))))
        assert spec == P((DATA_AXIS, "ep"))
        assert isinstance(spec[0], tuple)

    def test_unserializable_leaf_raises(self):
        with pytest.raises(TypeError, match="spec tree"):
            encode_spec_tree({"bad": object()})


# ---------------------------------------------------------------------------
# Plan round-trip, every strategy rung


class TestPlanRoundTrip:
    @pytest.mark.parametrize("strategy", [
        "none", "gather_scatter", "all_reduce", "fused", "zero", "fsdp",
    ])
    def test_engine_strategies(self, devices, strategy):
        plan = _trainer(devices, strategy).sharding_plan()
        back = ShardingPlan.from_json(plan.to_json())
        assert back == plan
        assert back.strategy == plan.strategy

    def test_int8_compression_carries_comp_specs(self, devices):
        plan = _trainer(devices, "fused",
                        grad_compress="int8").sharding_plan()
        assert plan.comp_specs is not None
        back = ShardingPlan.from_json(plan.to_json())
        assert back == plan

    def test_save_load(self, devices, tmp_path):
        plan = _trainer(devices, "zero").sharding_plan()
        plan.save(str(tmp_path))
        assert ShardingPlan.load(str(tmp_path)) == plan
        assert ShardingPlan.load(str(tmp_path / "missing")) is None

    def test_lm_trainer_rungs(self, devices):
        # tp and sp shard the PROGRAM: their specs must survive the
        # round-trip exactly (a dropped mp axis would re-place tensor-
        # parallel weights replicated after a membership change).
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.train.lm import LMTrainer
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        for kw in ({"dp": 2, "mp": 2}, {"dp": 2, "sp": 2}):
            mesh = make_mesh(devices[:4], **kw)
            plan = LMTrainer(model, mesh).sharding_plan()
            back = ShardingPlan.from_json(plan.to_json())
            assert back == plan

    def test_pipeline_trainer_rung(self, devices):
        from tpu_ddp.models.transformer import make_transformer
        from tpu_ddp.train.lm import PipelineLMTrainer
        model = make_transformer("TransformerLM-tiny", max_seq_len=32,
                                 compute_dtype=jnp.float32)
        mesh = make_mesh(devices[:4], dp=2, pp=2)
        plan = PipelineLMTrainer(model, mesh,
                                 num_micro=2).sharding_plan()
        back = ShardingPlan.from_json(plan.to_json())
        assert back == plan

    def test_version_gate(self):
        with pytest.raises(ValueError, match="version"):
            ShardingPlan.from_json('{"version": 99}')


# ---------------------------------------------------------------------------
# Re-resolution against a different world


class TestResolveAxes:
    def _plan(self, axes):
        return ShardingPlan(strategy="fused", mesh_axes=axes,
                            param_specs=P(), opt_specs=P())

    def test_data_axis_absorbs_world_change(self):
        plan = self._plan(((DATA_AXIS, 4), ("mp", 2)))
        assert plan.resolve_axes(4) == {DATA_AXIS: 2, "mp": 2}
        assert plan.resolve_axes(16) == {DATA_AXIS: 8, "mp": 2}

    def test_model_axes_are_rigid(self):
        plan = self._plan(((DATA_AXIS, 2), ("mp", 2), ("pp", 2)))
        with pytest.raises(ValueError, match="model axes"):
            plan.resolve_axes(6)

    def test_compatible_with_ignores_world_size(self, devices):
        p4 = _trainer(devices, "zero", dp=4).sharding_plan()
        p2 = _trainer(devices, "zero", dp=2).sharding_plan()
        assert p4.compatible_with(p2)
        assert p4 != p2  # mesh_axes differ


# ---------------------------------------------------------------------------
# Redistribution: bitwise vs a fresh shard of the same canonical bytes


class TestRedistribute:
    def test_same_plan_same_mesh_is_identity(self, devices):
        tr = _trainer(devices, "fused")
        state = tr.init_state()
        assert redistribute_state(state, tr, tr) is state

    def test_fused_dp4_to_dp2_bitwise(self, devices):
        src = _trainer(devices, "fused", dp=4)
        state = _advance(src, src.init_state())
        canonical = src.state_to_host(state)
        dst = _trainer(devices, "fused", dp=2)
        redist = redistribute_state(state, src, dst)
        assert redist.step == state.step
        _assert_host_trees_bitwise(dst.state_to_host(redist), canonical)
        # Placement matches the destination plan, not just the bytes.
        want = broadcast_shardings(dst.mesh, dst.sharding_plan()
                                   .param_specs, redist.params)
        got_spec = jax.tree.leaves(redist.params)[0].sharding.spec
        assert got_spec == jax.tree.leaves(want)[0].spec

    @pytest.mark.parametrize("strategy", ["zero", "fsdp"])
    def test_flat_layouts_repartition_bitwise(self, devices, strategy):
        # ZeRO/FSDP hold dp-PADDED flat shards on device: dp=4 and dp=2
        # bytes differ on device but must agree canonically.
        src = _trainer(devices, strategy, dp=4)
        state = _advance(src, src.init_state())
        canonical = src.state_to_host(state)
        dst = _trainer(devices, strategy, dp=2)
        redist = redistribute_state(state, src, dst)
        _assert_host_trees_bitwise(dst.state_to_host(redist), canonical)

    def test_int8_residual_same_dp_bitwise(self, devices):
        src = _trainer(devices, "fused", dp=4, grad_compress="int8")
        state = _advance(src, src.init_state())
        assert state.comp_state is not None
        canonical = src.state_to_host(state)
        dst = _trainer(devices, "fused", dp=4, grad_compress="int8")
        redist = redistribute_state(state, src, dst)
        _assert_host_trees_bitwise(dst.state_to_host(redist), canonical)

    def test_int8_residual_resets_on_dp_change(self, devices):
        # The error-feedback residual is dp-sharded by construction;
        # a dp change reshapes it, so the move must warn + reset — and
        # params/opt must still carry bitwise.
        src = _trainer(devices, "fused", dp=4, grad_compress="int8")
        state = _advance(src, src.init_state())
        canonical = src.state_to_host(state)
        dst = _trainer(devices, "fused", dp=2, grad_compress="int8")
        with pytest.warns(UserWarning, match="resetting"):
            redist = redistribute_state(state, src, dst)
        fresh = dst.compressor.init_state(dst._params_template(), 2,
                                          seed=dst.config.seed)
        _assert_host_trees_bitwise(
            jax.device_get(redist.comp_state), jax.device_get(fresh))
        got = dst.state_to_host(redist)
        for part in ("params", "opt_state"):
            _assert_host_trees_bitwise(got[part], canonical[part])


# ---------------------------------------------------------------------------
# Checkpoint restore across world sizes, routed through the saved plan


class TestCrossWorldCheckpoint:
    @pytest.mark.parametrize("save_dp,restore_dp", [
        (4, 2), (4, 8), (2, 4),
    ])
    def test_sha256_identical_across_dp(self, devices, tmp_path,
                                        save_dp, restore_dp):
        # "zero" is the strategy where dp changes the DEVICE bytes
        # (flat dp-padded opt shards) — the cell that would catch a
        # restore that forgot to re-partition.
        src = _trainer(devices, "zero", dp=save_dp)
        state = _advance(src, src.init_state())
        src.save_checkpoint(str(tmp_path), state)
        assert (tmp_path / "sharding_plan.json").exists()
        digest = _sha256(src.state_to_host(state))

        dst = _trainer(devices, "zero", dp=restore_dp)
        restored = dst.restore_checkpoint(str(tmp_path))
        assert restored.step == state.step
        assert _sha256(dst.state_to_host(restored)) == digest

    def test_cross_strategy_restore_warns(self, devices, tmp_path):
        src = _trainer(devices, "fused", dp=2)
        state = src.init_state()
        src.save_checkpoint(str(tmp_path), state)
        dst = _trainer(devices, "zero", dp=2)
        with pytest.warns(UserWarning, match="layout"):
            restored = dst.restore_checkpoint(str(tmp_path))
        assert _sha256(dst.state_to_host(restored)) == \
            _sha256(src.state_to_host(state))
