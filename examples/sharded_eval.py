"""Process-sharded evaluation demo — multi-process `evaluate(sharded=True)`.

The reference evaluates the full test set redundantly on every node
(reference part2/part2b/main.py:89-93). This CLI demonstrates the
TPU-native alternative for multi-process clusters: the test set is
sharded BY PROCESS in the loader (`create_data_loaders(shard_eval=True)`
— wrap-padding rows carry weight 0 so each example counts once
globally), each process's shard assembles into the global batch, and
the per-shard sums psum over dp. It runs BOTH evals and prints both
lines, so callers (tests/test_multiprocess.py) can assert the sharded
metrics equal the replicated ones.

Honours the reference launch contract, so the launcher can spawn it::

    python -m tpu_ddp.launch examples/sharded_eval.py --nproc 2

Env knobs: TPU_DDP_SYNTH_SIZE, TPU_DDP_GLOBAL_BATCH.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "parts"))

from common import parse_arguments  # noqa: E402


def main(argv=None) -> int:
    args = parse_arguments(argv, require_num_nodes=True)

    import jax

    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)

    import numpy as np

    from tpu_ddp.data.loader import create_data_loaders
    from tpu_ddp.models import get_model
    from tpu_ddp.parallel.bootstrap import (get_rank_from_hostname,
                                            init_distributed_setup,
                                            shutdown,
                                            test_distributed_setup)
    from tpu_ddp.parallel.mesh import make_mesh
    from tpu_ddp.train.engine import Trainer
    from tpu_ddp.utils.config import TrainConfig

    world = args.num_nodes or 1
    rank = (0 if world <= 1
            else args.rank if args.rank is not None
            else get_rank_from_hostname())
    ctx = init_distributed_setup(args.master_ip, args.master_port, rank,
                                 world)
    if world > 1:
        test_distributed_setup(ctx)

    # ViT, not VGG: batch-statistics BatchNorm (the VGG family's
    # reference-faithful semantic) computes its statistics over the
    # SHARD under sharded eval, so only per-example models (LayerNorm)
    # give bit-identical replicated-vs-sharded metrics to assert on.
    cfg = TrainConfig.preset("vit_cifar10")
    model = get_model(cfg.model, num_classes=cfg.num_classes,
                      compute_dtype=np.float32)
    mesh = make_mesh()
    trainer = Trainer(model, cfg, strategy="fused", mesh=mesh)
    state = trainer.init_state()
    print(f"[sharded_eval] rank={rank} world={world} "
          f"dp={mesh.shape['dp']}")

    batch = cfg.per_node_batch_size(world)
    # Replicated loader (the reference default) AND the process-sharded
    # one; same underlying (deterministic synthetic) test set.
    _, test_repl = create_data_loaders(rank=rank, world_size=world,
                                       batch_size=batch)
    _, test_shard = create_data_loaders(rank=rank, world_size=world,
                                        batch_size=batch,
                                        shard_eval=True)

    repl = trainer.evaluate(
        state, test_repl,
        log=lambda s: print(f"[replicated] {s}", flush=True))
    shard = trainer.evaluate(
        state, test_shard, sharded=True,
        log=lambda s: print(f"[sharded] {s}", flush=True))

    # The invariant the test asserts: identical global counts. The loss
    # is the reference's AVERAGE OF PER-BATCH MEANS (part1/main.py:108),
    # so a ragged final batch is weighted differently when the batch
    # boundaries differ (replicated: N-per-batch; sharded: N*world) —
    # only when every batch is full do the two averages coincide, and
    # then they must agree to reduction-order tolerance.
    assert shard["seen"] == repl["seen"], (shard, repl)
    assert shard["correct"] == repl["correct"], (shard, repl)
    if repl["seen"] % (batch * world) == 0:
        assert abs(shard["test_loss"] - repl["test_loss"]) < 1e-4, (
            shard, repl)
    else:
        assert abs(shard["test_loss"] - repl["test_loss"]) < 5e-2, (
            shard, repl)
    print(f"[sharded_eval] agreement ok: seen={shard['seen']} "
          f"correct={shard['correct']}", flush=True)

    shutdown(ctx)
    return 0


if __name__ == "__main__":
    sys.exit(main())
