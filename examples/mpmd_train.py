"""2-process MPMD pipeline drill — per-stage programs over socket edges.

Each process IS one pipeline slice: it compiles only its stage's
forward/backward (parallel/mpmd.py StageProgram), holds only its
stage's params + optimizer state, and exchanges activations/cotangents
with its peer over a TCP socket edge carrying the round-7 wire formats
(the DCN stand-in). No jax.distributed, no collectives — the edge IS
the only communication, which is the whole point of the MPMD model.

Honours the reference launch contract so the cluster launcher can
spawn it::

    python -m tpu_ddp.launch examples/mpmd_train.py --nproc 2

Env knobs: TPU_DDP_MPMD_STEPS (default 4), TPU_DDP_MPMD_COMPRESS
(none|bf16|int8|int8-noef — the CROSS-SLICE edge wire format; default
bf16), TPU_DDP_MPMD_MICRO (microbatches, default 4), TPU_DDP_LM_PRESET.

Exit contract (tests/test_mpmd.py's slow drill asserts it): exit 0
with a final ``[mpmd] RESULT ...`` line on the last stage showing the
loss decreased and the edge compression ratio matched the wire format;
exit 1 otherwise.
"""

import os
import socket
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "parts"))

from common import parse_arguments  # noqa: E402

PP = 2  # two processes, one stage each


def _connect(rank: int, ip: str, port: int) -> socket.socket:
    """Stage 1 listens, stage 0 dials (with retry — the launcher gives
    no start-order guarantee). One TCP connection, full duplex: the
    down edge (activations) and up edge (cotangents) share it."""
    if rank == 1:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", port))
        srv.listen(1)
        conn, _ = srv.accept()
        srv.close()
        return conn
    deadline = time.time() + 60
    while True:
        try:
            return socket.create_connection((ip, port), timeout=5)
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)


def main(argv=None) -> int:
    args = parse_arguments(argv, require_num_nodes=True)
    if args.num_nodes != PP:
        raise SystemExit(f"mpmd_train is a {PP}-process drill "
                         f"(got --num-nodes {args.num_nodes})")
    rank = args.rank if args.rank is not None else 0

    import jax
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)
    import jax.numpy as jnp
    import numpy as np

    from tpu_ddp.models import make_transformer
    from tpu_ddp.ops.optim import SGD
    from tpu_ddp.parallel.compress import EdgeCodec
    from tpu_ddp.parallel.mpmd import (MPMDPipeline, SliceTopology,
                                       SocketEdge, split_stage_params)
    from tpu_ddp.parallel.pipeline import stack_block_params
    from tpu_ddp.train.pipeline import StageScheduler

    steps = int(os.environ.get("TPU_DDP_MPMD_STEPS", "4"))
    spec = os.environ.get("TPU_DDP_MPMD_COMPRESS", "bf16")
    num_micro = int(os.environ.get("TPU_DDP_MPMD_MICRO", "4"))
    preset = os.environ.get("TPU_DDP_LM_PRESET", "TransformerLM-tiny")
    seq_len = 32
    batch = 2 * num_micro

    model = make_transformer(preset, max_seq_len=seq_len,
                             compute_dtype=np.float32)
    # Both processes derive the SAME init from the same seed, then keep
    # only their stage's partition — no broadcast needed.
    params = stack_block_params(model.init(jax.random.key(0)))
    params_s = split_stage_params(params, PP)[rank]

    # The edge: both directions over one socket; each process owns the
    # codec of its SENDING direction (error-feedback residuals are
    # sender state). The two stages are two "slices" here, so the one
    # boundary is cross-slice and carries the compressed format.
    sock = _connect(rank, args.master_ip, int(args.master_port))
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    edge = SocketEdge(sock, EdgeCodec(spec, seed=rank))

    sched = StageScheduler(PP, depth=2)
    pipe = MPMDPipeline(model, PP, seq_len, num_micro=num_micro,
                        topology=SliceTopology.even(PP, PP),
                        compress=spec, scheduler=sched)

    rng = np.random.default_rng(1234)
    tokens = rng.integers(0, model.vocab_size,
                          size=(batch, seq_len + 1)).astype(np.int32)
    x, y = tokens[:, :-1], tokens[:, 1:]
    mb = batch // num_micro
    micro = x.reshape(num_micro, mb, seq_len)
    tmicro = y.reshape(num_micro, mb, seq_len)
    denom = float(batch * seq_len)

    opt = SGD(learning_rate=0.1, momentum=0.9)
    opt_state = opt.init(params_s)
    losses = []
    for step in range(steps):
        if rank == 0:
            grads, _ = pipe.run_stage(0, params_s, micro, None,
                                      None, edge, edge, None)
        else:
            grads, loss_sum = pipe.run_stage(1, params_s, None, tmicro,
                                             edge, None, None, edge)
            losses.append(float(np.asarray(loss_sum)) / denom)
            print(f"[mpmd] rank={rank} step {step + 1}/{steps} "
                  f"loss {losses[-1]:.4f}", flush=True)
        grads = jax.tree.map(
            lambda g: g.astype(jnp.float32) / denom, grads)
        params_s, opt_state = opt.apply(params_s, grads, opt_state)
        sched.step_done(step)

    stats = edge.stats()
    print(f"[mpmd] rank={rank} edge {stats}", flush=True)
    print(f"[mpmd] rank={rank} sched "
          f"{sched.stats()['stages'][rank]}", flush=True)
    sock.close()
    if rank == 1:
        want = {"none": 1.0, "bf16": 1.9, "int8": 3.5,
                "int8-noef": 3.5}[spec]
        ok = losses[-1] < losses[0] and stats["ratio"] >= want
        print(f"[mpmd] RESULT loss {losses[0]:.4f}->{losses[-1]:.4f} "
              f"ratio {stats['ratio']} ({spec}) "
              f"{'OK' if ok else 'FAIL'}", flush=True)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
