"""End-to-end LM walkthrough: raw text -> packed batches -> distributed
training -> sampling.

The complete LM story in one file (the text-side analogue of the CIFAR
ladder parts): byte-level tokenization and C++-packed training rows
(tpu_ddp/data/text.py), an LMTrainer over the local device mesh with
dropout + a warmup-cosine AdamW schedule, checkpointing, and greedy
sampling from the trained model.

Run anywhere (no downloads — the corpus is inline)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/lm_text_train.py

Env knobs: TPU_DDP_LM_TEXT_EPOCHS (default 3), TPU_DDP_LM_TEXT_BATCH
(default 8), TPU_DDP_CKPT_DIR (optional checkpoint directory).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# A tiny self-contained corpus: structure the model can learn in a few
# epochs of byte-level training.
CORPUS = [
    "the quick brown fox jumps over the lazy dog. ",
    "pack my box with five dozen liquor jugs. ",
    "how vexingly quick daft zebras jump! ",
    "the five boxing wizards jump quickly. ",
] * 24


def main() -> int:
    import jax

    # Not a no-op: some environments pre-import jax from a site hook
    # that programmatically overrides jax_platforms AFTER the env var
    # was read — re-assert the user's choice (same as lm_train.py).
    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)

    import numpy as np

    from tpu_ddp.data.text import (ByteTokenizer, epoch_batches,
                                   pack_documents)
    from tpu_ddp.models import make_transformer
    from tpu_ddp.models.generate import generate
    from tpu_ddp.ops.optim import AdamW, warmup_cosine
    from tpu_ddp.parallel.mesh import make_mesh
    from tpu_ddp.train.lm import LMTrainer

    epochs = int(os.environ.get("TPU_DDP_LM_TEXT_EPOCHS", "3"))
    batch = int(os.environ.get("TPU_DDP_LM_TEXT_BATCH", "8"))
    ckpt_dir = os.environ.get("TPU_DDP_CKPT_DIR")
    seq_len = 64

    tok = ByteTokenizer()
    rows = pack_documents(CORPUS, seq_len=seq_len)
    print(f"[lm_text] corpus: {len(CORPUS)} docs -> {rows.shape[0]} rows "
          f"of {seq_len + 1} tokens (vocab {tok.vocab_size})")

    if batch > rows.shape[0]:
        raise SystemExit(
            f"[lm_text] TPU_DDP_LM_TEXT_BATCH={batch} exceeds the "
            f"{rows.shape[0]} packed rows — every epoch would be empty "
            f"(drop_last); lower the batch or grow the corpus")
    model = make_transformer(
        "TransformerLM-tiny", vocab_size=tok.vocab_size,
        max_seq_len=seq_len, dropout_rate=0.05)
    mesh = make_mesh()
    # Schedule length = the steps that actually run (drop_last floors).
    steps_per_epoch = rows.shape[0] // batch
    total_steps = steps_per_epoch * epochs
    trainer = LMTrainer(
        model, mesh,
        optimizer=AdamW(learning_rate=warmup_cosine(
            3e-3, max(total_steps // 6, 1), max(total_steps, 2))))
    state = trainer.init_state(seed=0)
    print(f"[lm_text] {model.num_params(state.params):,} params on mesh "
          f"{dict(mesh.shape)}")

    for epoch in range(epochs):
        losses = []
        for inp, tgt in epoch_batches(rows, batch, seed=17, epoch=epoch):
            x, y = trainer.put_batch(inp, tgt)
            state, loss = trainer.train_step(state, x, y)
            losses.append(float(np.mean(np.asarray(loss))))
        print(f"[lm_text] epoch {epoch}: mean loss "
              f"{np.mean(losses):.4f} over {len(losses)} steps")
    if ckpt_dir:
        path = trainer.save_checkpoint(ckpt_dir, state)
        print(f"[lm_text] checkpoint: {path}")

    # Sample from the trained model: `model` is already dense (this mesh
    # has sp=tp=ep=1, and LMTrainer never mutates the caller's copy);
    # generate passes no rng, so dropout is inert at decode time.
    params = jax.device_get(state.params)
    prompt = tok.encode("the quick brown ")[None, :]
    out = generate(model, params, prompt, max_new_tokens=24)
    print(f"[lm_text] sample: {tok.decode(prompt[0])!r} -> "
          f"{tok.decode(np.asarray(out)[0])!r}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
