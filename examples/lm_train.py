"""Distributed transformer-LM training demo — the LM-engine analogue of
the parts/ CLIs.

Honours the reference launch contract (reference README.md:8-19), so the
local cluster launcher can spawn it::

    python -m tpu_ddp.launch examples/lm_train.py --nproc 2

or run it per node like any part::

    python examples/lm_train.py --num-nodes N --rank R \
        --master-ip IP --master-port P

Each process contributes its local devices as dp slots; batches are
synthetic tokens (zero egress), per-process shards assembled into global
arrays by the trainer. Env knobs: TPU_DDP_LM_STEPS, TPU_DDP_LM_PRESET,
TPU_DDP_LM_FSDP=1, TPU_DDP_GLOBAL_BATCH, TPU_DDP_LM_ACCUM (gradient-
accumulation microbatches), TPU_DDP_LM_SP_MODE (ring|ulysses),
TPU_DDP_LM_OPT (adamw|adafactor), TPU_DDP_LM_ZERO1=1 (ZeRO-1 optimizer
state sharding — Adafactor uses the row-sharded FactoredZeRO1; with
TPU_DDP_LM_TP>1 the elementwise wrapper lays tp-sharded leaves' state
out P((mp, dp))), TPU_DDP_LM_TP (Megatron tensor-parallel extent).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "parts"))

from common import parse_arguments  # noqa: E402


def main(argv=None) -> int:
    args = parse_arguments(argv, require_num_nodes=True)

    import jax

    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        jax.config.update("jax_platforms", env_platforms)

    import numpy as np

    from tpu_ddp.models import make_transformer
    from tpu_ddp.parallel.bootstrap import (get_rank_from_hostname,
                                            init_distributed_setup,
                                            shutdown,
                                            test_distributed_setup)
    from tpu_ddp.parallel.mesh import make_mesh
    from tpu_ddp.train.lm import (LMTrainer, PipelineLMTrainer,
                                  make_lm_batch)

    world = args.num_nodes or 1
    rank = (0 if world <= 1
            else args.rank if args.rank is not None
            else get_rank_from_hostname())
    ctx = init_distributed_setup(args.master_ip, args.master_port, rank,
                                 world)
    if world > 1:
        test_distributed_setup(ctx)

    steps = int(os.environ.get("TPU_DDP_LM_STEPS", "5"))
    preset = os.environ.get("TPU_DDP_LM_PRESET", "TransformerLM-tiny")
    fsdp = os.environ.get("TPU_DDP_LM_FSDP", "0") == "1"
    accum = int(os.environ.get("TPU_DDP_LM_ACCUM", "1"))
    sp_mode = os.environ.get("TPU_DDP_LM_SP_MODE", "ring")
    # TPU_DDP_LM_OPT_SHARD: replicated | zero1 | zero2 (zero2 =
    # dp-scattered grad accumulation; pair with TPU_DDP_LM_ACCUM).
    # TPU_DDP_LM_ZERO1=1 is the legacy spelling of zero1.
    opt_shard = os.environ.get(
        "TPU_DDP_LM_OPT_SHARD",
        "zero1" if os.environ.get("TPU_DDP_LM_ZERO1", "0") == "1"
        else "replicated")
    # TPU_DDP_LM_CLIP: global-norm gradient clip threshold (0 = off).
    clip = float(os.environ.get("TPU_DDP_LM_CLIP", "0")) or None
    opt_name = os.environ.get("TPU_DDP_LM_OPT", "adamw")
    tp = int(os.environ.get("TPU_DDP_LM_TP", "1"))
    if tp < 1:
        raise ValueError(f"TPU_DDP_LM_TP={tp}: must be >= 1")
    # TPU_DDP_LM_PP>1 selects the pipeline rung; the schedule knobs
    # (TPU_DDP_PP_SCHEDULE / TPU_DDP_PP_MICROBATCHES /
    # TPU_DDP_PP_VIRTUAL) ride in through TrainConfig's env parsing so
    # the launch flags (--pp-schedule etc.) reach this CLI unchanged.
    pp = int(os.environ.get("TPU_DDP_LM_PP", "1"))
    if pp < 1:
        raise ValueError(f"TPU_DDP_LM_PP={pp}: must be >= 1")
    from tpu_ddp.utils.config import TrainConfig
    knobs = TrainConfig()
    pp_schedule = knobs.pp_schedule
    pp_micro = knobs.pp_microbatches or None   # 0 = auto (= pp)
    pp_virtual = knobs.pp_virtual
    global_batch = int(os.environ.get("TPU_DDP_GLOBAL_BATCH", "8"))
    # The batch axis shards over dp PROCESS GROUPS (world // tp), not
    # over every process: tp-group members feed the same rows.
    dp_groups = max(world // tp, 1)
    if global_batch % dp_groups:
        raise ValueError(f"TPU_DDP_GLOBAL_BATCH={global_batch} not "
                         f"divisible by dp process groups {dp_groups} "
                         f"(world {world} / tp {tp})")
    seq_len = 32

    model = make_transformer(preset, max_seq_len=seq_len,
                             compute_dtype=np.float32)
    mesh = make_mesh(mp=tp)
    if opt_name == "adafactor":
        from tpu_ddp.ops.optim import Adafactor
        optimizer = Adafactor(min_dim_size_to_factor=8)
    elif opt_name == "adamw":
        optimizer = None  # LMTrainer's AdamW default
    else:
        raise ValueError(f"TPU_DDP_LM_OPT={opt_name!r}: expected "
                         "'adamw' or 'adafactor'")
    if pp > 1:
        mesh = make_mesh(mp=tp, pp=pp)
        trainer = PipelineLMTrainer(
            model, mesh,
            num_micro=pp_micro,
            schedule=pp_schedule,
            pp_virtual=pp_virtual,
            param_sharding="fsdp" if fsdp else "replicated",
            opt_sharding=opt_shard,
            optimizer=optimizer,
            sp_mode=sp_mode, clip_grad_norm=clip)
    else:
        trainer = LMTrainer(
            model, mesh,
            param_sharding="fsdp" if fsdp else "replicated",
            opt_sharding=opt_shard,
            optimizer=optimizer,
            grad_accum=accum, sp_mode=sp_mode, clip_grad_norm=clip)
    state = trainer.init_state(seed=0)
    print(f"[lm_train] rank={rank} world={world} dp={trainer.dp} "
          f"sp={trainer.sp} tp={trainer.tp} pp={pp} fsdp={fsdp} "
          f"opt_shard={opt_shard} opt={opt_name} accum={accum} "
          f"clip={clip} preset={preset}"
          + (f" schedule={pp_schedule} micro={trainer.num_micro} "
             f"virtual={pp_virtual}" if pp > 1 else ""))

    # Deterministic synthetic tokens, identical on every process; each
    # process feeds ITS contiguous shard of the global batch.
    rng = np.random.default_rng(1234)
    tokens = rng.integers(0, model.vocab_size,
                          size=(global_batch, seq_len + 1))
    # Each PROCESS feeds its shard of the batch axis; with tp the
    # batch only shards over dp = world/tp process groups, so processes
    # in the same tp group feed the SAME rows (put_batch assembles by
    # process index; dp-major mesh order makes rank // tp the dp slot).
    # tp == 1 reduces to the plain per-rank split (slot == rank).
    per = global_batch // dp_groups
    slot = rank // tp
    local = tokens[slot * per:(slot + 1) * per]
    x, y = trainer.put_batch(*make_lm_batch(local))
    for step in range(steps):
        state, loss = trainer.train_step(state, x, y)
        # THIS process's shard losses (the global array is not fully
        # addressable across processes) — every node prints its own
        # running loss, as in the reference.
        mean = float(np.mean([np.asarray(s.data)
                              for s in loss.addressable_shards]))
        print(f"[lm_train] step {step + 1}/{steps} loss {mean:.4f}")
    shutdown(ctx)
    return 0


if __name__ == "__main__":
    sys.exit(main())
